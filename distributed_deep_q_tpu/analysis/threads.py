"""Thread-lifecycle registry — every spawn site is a declared contract.

The fleet is deeply threaded (accept/serve loops on two RPC servers,
the ingest drain, the snapshot writer, the flow watchdog, the actor
heartbeat and supervisor watch loop), and each thread's lifecycle —
who owns it, what stops it, who joins it — was comment-folklore. This
pass makes the contract declarative and machine-checked:

- ``ThreadSpec`` registers one spawn site by (file, target) and names
  the thread, its owner, its stop mechanism, and its join/shutdown
  site. An unregistered ``threading.Thread(...)`` in a walked file is
  ``threads.unregistered``; a spawn whose ``name=``/``daemon=`` kwargs
  disagree with the spec is ``threads.spec-mismatch``.
- Non-daemon threads must have a reachable join: the spec names the
  method (``joined_in``) and the checker verifies a ``.join(`` on the
  attribute the spawn was stored to actually exists there —
  ``threads.no-join`` otherwise. Daemon threads may skip the join only
  with a stated ``why_no_join`` reason in the spec.
- Stop mechanisms are verified, not trusted: an ``("event", attr)``
  stop needs a ``<attr>.set()`` call somewhere in the file (a stop
  event nobody sets is an unstoppable thread → ``threads.no-stop``);
  a ``("lock-release", attr)`` stop (the snapshot writer is bounded by
  releasing ``_snap_lock``) needs the ``.release()`` inside the target;
  a ``("flag", attr, guard)`` stop is a plain bool whose every write
  must sit under ``with <recv>.<guard>:`` — ``threads.stop-unguarded``
  otherwise (the IngestDrain/InferenceServer shutdown flags move under
  their condition variables). ``("connection", why)`` declares a
  per-connection serve thread reaped by peer close / socket deadline —
  nothing to verify beyond the registration itself.

Registering a new thread = one ``ThreadSpec`` line in
``DEFAULT_THREADS``; an unregistered spawn fails the gate.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from distributed_deep_q_tpu.analysis.core import (
    Finding, Source, call_name, dotted, load_sources)

RULE_UNREGISTERED = "threads.unregistered"
RULE_MISMATCH = "threads.spec-mismatch"
RULE_NO_JOIN = "threads.no-join"
RULE_NO_STOP = "threads.no-stop"
RULE_STOP_UNGUARDED = "threads.stop-unguarded"


@dataclass(frozen=True)
class ThreadSpec:
    """One registered spawn site: the lifecycle contract of a thread."""

    name: str                 # thread-name literal the spawn must pass
    owner: str                # owning class (or enclosing function)
    stop: tuple               # ("event", attr) | ("flag", attr, guard) |
    #                           ("lock-release", attr) | ("connection", why)
    joined_in: str | None     # method containing the join; None = no join
    why_no_join: str = ""     # required rationale when joined_in is None
    daemon: bool = True


@dataclass
class ThreadRegistry:
    # (repo-relative file, target callable's tail name) → spec
    specs: dict[tuple[str, str], ThreadSpec] = field(default_factory=dict)
    # methods that run single-threaded (construction / warm boot) —
    # stop-flag writes there need no guard
    unlocked_methods: frozenset = frozenset(
        {"__init__", "_restore", "_load_generation", "_reset_boot_state"})
    files: tuple[str, ...] = ()


DEFAULT_THREADS = ThreadRegistry(
    specs={
        # actor heartbeat (_ActorComms): paced on a PROCESS-LOCAL event —
        # never the shared mp stop event (a SIGKILL'd sleeper would
        # deadlock the supervisor's notify_all). Daemon dies with the
        # process; clean exits set _local_stop from close()
        ("distributed_deep_q_tpu/actors/supervisor.py", "_beat"):
            ThreadSpec(
                name="actor-heartbeat", owner="_ActorComms",
                stop=("event", "_local_stop"), joined_in=None,
                why_no_join="close() sets the process-local stop and the "
                            "beat exits within one backoff period; joining "
                            "would stall actor teardown on a sleeping "
                            "backoff"),
        # supervisor watch loop: polls process liveness + heartbeat
        # silence; exits on the shared mp stop event checked every poll
        ("distributed_deep_q_tpu/actors/supervisor.py", "loop"):
            ThreadSpec(
                name="actor-supervisor", owner="ActorSupervisor",
                stop=("event", "stop_event"), joined_in=None,
                why_no_join="stop() sets the mp stop event and joins the "
                            "actor PROCESSES; the daemon watch loop exits "
                            "on its next poll tick"),
        # replay feed: accept loop joined by close() after the socket
        # shutdown unblocks accept()
        ("distributed_deep_q_tpu/rpc/replay_server.py", "_accept_loop"):
            ThreadSpec(
                name="replayfeed-accept", owner="ReplayFeedServer",
                stop=("event", "_stop"), joined_in="close"),
        # async snapshot writer: bounded by one serialize+fsync; holds
        # ONLY _snap_lock (captured state travels by argument), so
        # shutdown serializes against it via snapshot()'s lock acquire,
        # not a join
        ("distributed_deep_q_tpu/rpc/replay_server.py",
         "_write_and_release"):
            ThreadSpec(
                name="replayfeed-snapshot", owner="ReplayFeedServer",
                stop=("lock-release", "_snap_lock"), joined_in=None,
                why_no_join="bounded by one serialize+fsync; shutdown "
                            "serializes on _snap_lock, which the thread "
                            "releases in its finally"),
        # per-connection serve threads: reaped by peer close or close()
        # closing every tracked conn; the socket deadline bounds a wedge
        ("distributed_deep_q_tpu/rpc/replay_server.py", "_serve"):
            ThreadSpec(
                name="replayfeed-serve", owner="ReplayFeedServer",
                stop=("connection", "close() closes every conn in "
                      "_conns; recv then raises"), joined_in=None,
                why_no_join="per-connection; exits when its socket dies"),
        # inference plane: batcher drains on the _closed flag (under
        # _cv), accept loop on the _stop event; both joined by close()
        ("distributed_deep_q_tpu/rpc/inference_server.py", "_batch_loop"):
            ThreadSpec(
                name="infer-batch", owner="InferenceServer",
                stop=("flag", "_closed", "_cv"), joined_in="close"),
        ("distributed_deep_q_tpu/rpc/inference_server.py", "_accept_loop"):
            ThreadSpec(
                name="infer-accept", owner="InferenceServer",
                stop=("event", "_stop"), joined_in="close"),
        ("distributed_deep_q_tpu/rpc/inference_server.py", "_serve"):
            ThreadSpec(
                name="infer-serve", owner="InferenceServer",
                stop=("connection", "close() closes every conn in "
                      "_conns; recv then raises"), joined_in=None,
                why_no_join="per-connection; exits when its socket dies"),
        # flow-control watchdog: wakes on _stop.wait(period), joined by
        # close()
        ("distributed_deep_q_tpu/rpc/flowcontrol.py", "_watch_loop"):
            ThreadSpec(
                name="flow-watchdog", owner="FlowController",
                stop=("event", "_stop"), joined_in="close"),
        # device stager: sample-under-lock / device_put-off-lock
        # pipeline; joined by close() after draining the queue so a
        # blocked put() can observe the stop flag
        ("distributed_deep_q_tpu/replay/staging.py", "_run"):
            ThreadSpec(
                name="replay-stager", owner="DeviceStager",
                stop=("event", "_stop"), joined_in="close"),
        # ingest drain: stop flag moves under its condition variable
        # (set + notify in close()), joined by close() before the final
        # stranded-rows work unit
        ("distributed_deep_q_tpu/replay/columnar.py", "_run"):
            ThreadSpec(
                name="ingest-drain", owner="IngestDrain",
                stop=("flag", "_stop", "_cv"), joined_in="close"),
    },
    files=(
        "distributed_deep_q_tpu/rpc/flowcontrol.py",
        "distributed_deep_q_tpu/rpc/replay_server.py",
        "distributed_deep_q_tpu/rpc/inference_server.py",
        "distributed_deep_q_tpu/actors/supervisor.py",
        "distributed_deep_q_tpu/actors/membership.py",
        "distributed_deep_q_tpu/actors/autoscaler.py",
        "distributed_deep_q_tpu/replay/staging.py",
        "distributed_deep_q_tpu/replay/columnar.py",
    ),
)


def _is_thread_call(node: ast.Call) -> bool:
    name = call_name(node)
    return name is not None and name.rsplit(".", 1)[-1] == "Thread"


def _kwarg(node: ast.Call, key: str) -> ast.AST | None:
    for kw in node.keywords:
        if kw.arg == key:
            return kw.value
    return None


def _const(node: ast.AST | None):
    return node.value if isinstance(node, ast.Constant) else None


def _tail(name: str | None) -> str | None:
    return name.rsplit(".", 1)[-1] if name else None


def _spawn_sites(src: Source) -> list[tuple[ast.Call, str | None]]:
    """Every ``threading.Thread(...)`` call with the attribute it was
    stored to (``self._thread = Thread(...)`` → ``_thread``; a chained
    ``Thread(...).start()`` or bare call stores nothing → None)."""
    stored: dict[int, str] = {}
    for node in src.nodes(ast.Assign):
        if len(node.targets) == 1 \
                and isinstance(node.value, ast.Call) \
                and _is_thread_call(node.value):
            attr = _tail(dotted(node.targets[0]))
            if attr:
                stored[id(node.value)] = attr
    out: list[tuple[ast.Call, str | None]] = []
    for node in src.nodes(ast.Call):
        if _is_thread_call(node):
            out.append((node, stored.get(id(node))))
    return out


def _functions_named(src: Source, name: str) -> list[ast.FunctionDef]:
    return [n for n in src.nodes(ast.FunctionDef, ast.AsyncFunctionDef)
            if n.name == name]


def _calls_method_on(scope: ast.AST, method: str,
                     recv_tail: str | None = None) -> bool:
    """Is there a ``<recv>.<method>(...)`` call in ``scope``? When
    ``recv_tail`` is given, the receiver chain must end with it."""
    for node in ast.walk(scope):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute) \
                or node.func.attr != method:
            continue
        if recv_tail is None:
            return True
        recv = dotted(node.func.value)
        if recv is not None and recv.rsplit(".", 1)[-1] == recv_tail:
            return True
    return False


class _FlagWalker(ast.NodeVisitor):
    """Lexical walk flagging writes to a stop flag outside its guard."""

    def __init__(self, src: Source, flag: str, guard: str,
                 unlocked: frozenset, out: list[Finding]):
        self.src = src
        self.flag = flag
        self.guard = guard
        self.unlocked = unlocked
        self.out = out
        self.held = 0
        self.funcs: list[str] = []

    def _visit_func(self, node) -> None:
        self.funcs.append(getattr(node, "name", "<lambda>"))
        self.generic_visit(node)
        self.funcs.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_With(self, node: ast.With) -> None:
        taken = 0
        for item in node.items:
            expr = item.context_expr
            if (isinstance(expr, ast.Call) and expr.args
                    and (dotted(expr.func) or "").rsplit(".", 1)[-1]
                    == "locked"):
                expr = expr.args[0]
            name = dotted(expr)
            if name and name.rsplit(".", 1)[-1] == self.guard:
                self.held += 1
                taken += 1
        for stmt in node.body:
            self.visit(stmt)
        self.held -= taken

    visit_AsyncWith = visit_With

    def _check_target(self, target: ast.AST, node: ast.AST) -> None:
        if isinstance(target, ast.Attribute) and target.attr == self.flag \
                and not self.held \
                and not any(f in self.unlocked for f in self.funcs):
            self.src.finding(
                RULE_STOP_UNGUARDED, node,
                f"stop flag {self.flag!r} written outside "
                f"'with {self.guard}:' — the thread's exit check races "
                "this store", self.out)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_target(t, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_target(node.target, node)
        self.generic_visit(node)


def check_sources(sources: list[Source],
                  registry: ThreadRegistry = DEFAULT_THREADS
                  ) -> list[Finding]:
    out: list[Finding] = []
    for src in sources:
        relpath = src.path.replace(os.sep, "/")
        checked_flags: set[tuple[str, str]] = set()
        for call, stored_attr in _spawn_sites(src):
            target_name = _tail(dotted(_kwarg(call, "target")))
            spec = None
            if target_name is not None:
                for (file, target), s in registry.specs.items():
                    if target == target_name and relpath.endswith(file):
                        spec = s
                        break
            if spec is None:
                src.finding(
                    RULE_UNREGISTERED, call,
                    f"unregistered thread spawn (target="
                    f"{target_name or '<computed>'}): add a ThreadSpec "
                    "naming its owner, stop mechanism, and join site",
                    out)
                continue
            name = _const(_kwarg(call, "name"))
            if name != spec.name:
                src.finding(
                    RULE_MISMATCH, call,
                    f"thread spawn name={name!r} but the registered spec "
                    f"says {spec.name!r} — name every thread so stack "
                    "dumps attribute it", out)
            daemon = bool(_const(_kwarg(call, "daemon")))
            if daemon != spec.daemon:
                src.finding(
                    RULE_MISMATCH, call,
                    f"thread spawn daemon={daemon} but the registered "
                    f"spec says daemon={spec.daemon}", out)
            # join contract: non-daemon threads MUST have one; a spec
            # that declares one must be verifiable against the file
            if spec.joined_in is None:
                if not daemon:
                    src.finding(
                        RULE_NO_JOIN, call,
                        "non-daemon thread with no registered join site "
                        "— process exit will hang on it", out)
                elif not spec.why_no_join:
                    src.finding(
                        RULE_NO_JOIN, call,
                        "daemon thread skips its join without a stated "
                        "why_no_join reason in the spec", out)
            else:
                joiners = _functions_named(src, spec.joined_in)
                ok = stored_attr is not None and any(
                    _calls_method_on(fn, "join", stored_attr)
                    for fn in joiners)
                if not ok:
                    src.finding(
                        RULE_NO_JOIN, call,
                        f"spec says {spec.owner}.{spec.joined_in}() joins "
                        "this thread, but no .join() on the stored "
                        f"attribute ({stored_attr or 'not stored'}) was "
                        "found there", out)
            # stop contract
            kind = spec.stop[0] if spec.stop else None
            if kind == "event":
                attr = spec.stop[1]
                if not _calls_method_on(src.tree, "set", attr):
                    src.finding(
                        RULE_NO_STOP, call,
                        f"stop event {attr!r} is never .set() in this "
                        "file — the thread is unstoppable", out)
            elif kind == "lock-release":
                attr = spec.stop[1]
                targets = _functions_named(src, target_name)
                if not any(_calls_method_on(fn, "release", attr)
                           for fn in targets):
                    src.finding(
                        RULE_NO_STOP, call,
                        f"spec says the thread is bounded by releasing "
                        f"{attr!r}, but {target_name}() never releases "
                        "it", out)
            elif kind == "flag":
                flag, guard = spec.stop[1], spec.stop[2]
                if (flag, guard) not in checked_flags:
                    checked_flags.add((flag, guard))
                    _FlagWalker(src, flag, guard,
                                registry.unlocked_methods, out
                                ).visit(src.tree)
    return out


def check(repo_root: str,
          registry: ThreadRegistry = DEFAULT_THREADS) -> list[Finding]:
    paths = [os.path.join(repo_root, f) for f in registry.files
             if os.path.exists(os.path.join(repo_root, f))]
    return check_sources(load_sources(repo_root, paths), registry)
