"""distributed_deep_q_tpu — a TPU-native distributed deep Q-learning framework.

A ground-up rebuild of the capability surface of ``defc0n1/distributed-deep-q``
(Caffe + Spark + parameter-server DQN; see SURVEY.md) designed TPU-first:

- compute: Flax Q-networks compiled by XLA under ``jax.jit``; optional Pallas
  kernels for fused TD-loss (``ops/``),
- parallelism: synchronous data parallelism via ``shard_map`` + ``lax.psum``
  over a ``jax.sharding.Mesh`` (replacing the reference's Spark/param-server
  asynchronous gradient push/pull — BASELINE.json ``north_star`` [M]),
- actors: plain-Python CPU actor processes (``actors/game.py``) feeding a
  replay service over an RPC boundary (``rpc/``), unchanged in role from the
  reference's ``game.py`` / ``AtariEnv`` workers [M],
- replay: host-RAM ring buffers (uniform / prioritized / sequence) with an
  optional C++ native core (``native/``), streaming minibatches into the
  learner via a double-buffered host→device pipeline.

Reference provenance: the reference mount was empty in every session so far
(SURVEY.md §0); the authoritative capability surface is the driver-written
BASELINE.json ``north_star`` + ``configs`` ([M] claims), which this package
implements symbol-for-symbol (``Solver``, ``QNet``, ``ReplayMemory``,
``AtariEnv``, ``--backend``).
"""

__version__ = "0.1.0"

from distributed_deep_q_tpu.config import Config  # noqa: F401
from distributed_deep_q_tpu.solver import Solver  # noqa: F401
