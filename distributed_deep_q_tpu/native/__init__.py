"""Native (C++) replay core — build + ctypes loader.

The reference's native layer is external (Caffe C++/CUDA, ALE; SURVEY.md
§2.1); its own replay loops are Python. Here the PER sum-tree descent — the
one host-side pointer-chasing hot loop (SURVEY §7.3 item 2) — has a C++
implementation compiled on first use with the baked-in g++ toolchain
(no pybind11 in the image, so the ABI is plain C via ctypes).

``load()`` returns the ctypes lib or None (missing compiler, failed build);
callers fall back to the numpy implementation, which remains the semantic
reference. The build is cached next to the source and rebuilt only when
``replay_core.cpp`` is newer than the cached ``.so``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "replay_core.cpp")
_SO = os.path.join(_HERE, "_replay_core.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False

_c_double_p = ctypes.POINTER(ctypes.c_double)
_c_int64_p = ctypes.POINTER(ctypes.c_int64)
_c_u8p = ctypes.POINTER(ctypes.c_uint8)
_c_u8pp = ctypes.POINTER(_c_u8p)


def _build() -> bool:
    """Compile to a process-unique temp path, then rename into place —
    atomic on POSIX, so concurrent builders (supervisor-spawned actor
    processes all importing replay) can never leave a half-written .so."""
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           "-o", tmp, _SRC]
    try:
        subprocess.run(  # ddq: allow(blocking.under-lock) — build-once
            # gate: _lock exists to make the first caller compile while
            # the rest wait; nothing hot shares this module lock
            cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load() -> ctypes.CDLL | None:
    """Build (if needed) and load the native core; None on any failure."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        stale = (not os.path.exists(_SO)
                 or os.path.getmtime(_SO) < os.path.getmtime(_SRC))
        if stale and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            # cached artifact unloadable (foreign arch, corrupt file):
            # rebuild once before giving up
            if not _build():
                return None
            try:
                lib = ctypes.CDLL(_SO)
            except OSError:
                return None
        lib.st_set.argtypes = [_c_double_p, ctypes.c_int64, _c_int64_p,
                               _c_double_p, ctypes.c_int64]
        lib.st_set.restype = None
        lib.st_sample_stratified.argtypes = [
            _c_double_p, ctypes.c_int64, _c_double_p, _c_int64_p,
            ctypes.c_int64]
        lib.st_sample_stratified.restype = None
        lib.staged_append.argtypes = [
            _c_u8pp, _c_u8pp, _c_int64_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64]
        lib.staged_append.restype = ctypes.c_int64
        _lib = lib
        return _lib


def as_double_p(a) -> _c_double_p:
    return a.ctypes.data_as(_c_double_p)


def as_int64_p(a) -> _c_int64_p:
    return a.ctypes.data_as(_c_int64_p)


def as_uint8_p(a) -> _c_u8p:
    return a.ctypes.data_as(_c_u8p)


def uint8_pp(ptrs) -> _c_u8pp:
    """Pack an iterable of c_uint8 pointers into the pointer-array
    argument ``staged_append`` takes for its dst/src column tables."""
    arr = (_c_u8p * len(ptrs))(*ptrs)
    return ctypes.cast(arr, _c_u8pp)
