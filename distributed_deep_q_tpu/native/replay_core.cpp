// Native replay core — sum-tree inner loops (SURVEY.md §7.3 item 2).
//
// The reference keeps all native compute in external deps (Caffe/ALE,
// SURVEY §2.1); its replay is pure Python. The rebuild's host-side PER
// sampling is the one genuinely pointer-chasing hot loop left outside XLA
// (root→leaf descent per sample lane), so it gets a C++ core: the numpy
// implementation in replay/prioritized.py stays as the portable fallback
// and the reference semantics; this file must match it bit-for-bit on the
// float64 tree (tests/test_native.py asserts equivalence).
//
// Exposed via plain C ABI for ctypes (no pybind11 in the image). All
// buffers are caller-owned numpy arrays; nothing here allocates.

#include <cstdint>
#include <cstring>

extern "C" {

// Columnar staged append (ISSUE 8 ingest path): copy n rows of each of
// ncols columns into its caller-owned staging buffer at row `cursor`.
// dst[c] is the base of column c's staging buffer, src[c] the incoming
// contiguous segment, row_bytes[c] the column's row stride. One memcpy
// per COLUMN (not per row) — the whole point: the Python hot path pays
// O(columns) of call overhead per staged segment and zero per-row work.
// Returns the advanced cursor. Must stay bit-identical to the numpy
// fallback (`buf[cursor:cursor+n] = seg`), which remains the reference
// semantics (tests/test_columnar_ingest.py asserts equivalence).
int64_t staged_append(unsigned char* const* dst,
                      const unsigned char* const* src,
                      const int64_t* row_bytes, int64_t ncols,
                      int64_t cursor, int64_t n) {
  for (int64_t c = 0; c < ncols; ++c) {
    std::memcpy(dst[c] + cursor * row_bytes[c], src[c],
                static_cast<size_t>(n * row_bytes[c]));
  }
  return cursor + n;
}

// Set leaves tree[size + idx[k]] = p[k] (duplicates: last write wins, same
// as numpy fancy assignment), then repair ancestors bottom-up.
void st_set(double* tree, int64_t size, const int64_t* idx, const double* p,
            int64_t n) {
  for (int64_t k = 0; k < n; ++k) {
    tree[size + idx[k]] = p[k];
  }
  for (int64_t k = 0; k < n; ++k) {
    for (int64_t node = (size + idx[k]) >> 1; node >= 1; node >>= 1) {
      tree[node] = tree[2 * node] + tree[2 * node + 1];
    }
  }
}

// Stratified proportional sampling: lane k draws target
// (k + urand[k]) * total / n and descends root→leaf.
// Matches SumTree.sample_stratified (replay/prioritized.py).
void st_sample_stratified(const double* tree, int64_t size,
                          const double* urand, int64_t* out, int64_t n) {
  const double total = tree[1];
  const double stride = total / static_cast<double>(n);
  for (int64_t k = 0; k < n; ++k) {
    double target = (static_cast<double>(k) + urand[k]) * stride;
    int64_t node = 1;
    while (node < size) {
      const int64_t left = 2 * node;
      const double left_sum = tree[left];
      // strict '>' to match the numpy descent (targets > left_sum)
      if (target > left_sum) {
        target -= left_sum;
        node = left + 1;
      } else {
        node = left;
      }
    }
    out[k] = node - size;
  }
}

}  // extern "C"
