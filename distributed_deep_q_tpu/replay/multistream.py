"""Host-RAM multi-stream pixel replay — the ``device_resident=False``
fallback for the distributed pixel topology.

``FrameStackReplay`` (replay/replay_memory.py) requires one temporally
contiguous writer stream; the RPC fleet interleaves many. This wrapper gives
each actor stream its own ``FrameStackReplay`` shard (capacity split
evenly), preserving the adjacency invariant per shard — the host-side
analogue of the device ring's slot layout (replay/device_ring.py), with
pixels gathered on host and shipped as full minibatches (the path the
reference's Caffe blob loads took, SURVEY §3.1; measured cost in bench.py's
host-replay variant).

Uniform sampling only: PER over cross-shard global indices belongs to the
device ring; the distributed entry point rejects the
``prioritized && !device_resident`` combination explicitly.
"""

from __future__ import annotations

import numpy as np

from distributed_deep_q_tpu.replay.prioritized import allocate_proportional
from distributed_deep_q_tpu.replay.replay_memory import FrameStackReplay


class MultiStreamFrameReplay:
    """N per-stream ``FrameStackReplay`` shards behind one buffer surface."""

    prioritized = False

    def __init__(
        self,
        capacity: int,
        frame_shape: tuple[int, int] = (84, 84),
        stack: int = 4,
        n_step: int = 1,
        gamma: float = 0.99,
        num_streams: int = 1,
        seed: int = 0,
    ):
        self.num_streams = max(int(num_streams), 1)
        per = int(capacity) // self.num_streams
        assert per > stack + n_step + 2, (
            f"capacity {capacity} too small for {num_streams} streams")
        self.shard_cap = per
        self.capacity = per * self.num_streams
        self.shards = [
            FrameStackReplay(per, frame_shape, stack, n_step, gamma,
                             seed=seed + i)
            for i in range(self.num_streams)]
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return sum(len(s) for s in self.shards)

    @property
    def steps_added(self) -> int:
        return sum(s.steps_added for s in self.shards)

    def _sampleable(self, i: int) -> int:
        s = self.shards[i]
        window = s.stack + s.n_step + 1
        if len(s) <= window or s.valid_fraction() <= 0:
            return 0
        return len(s) - window

    def ready(self, learn_start: int) -> bool:
        return (len(self) >= learn_start
                and any(self._sampleable(i) for i in range(self.num_streams)))

    # -- write ---------------------------------------------------------------

    def add(self, frame, action, reward, done, boundary=None) -> int:
        return self.shards[0].add(frame, action, reward, done,
                                  boundary=boundary)

    def add_batch(self, batch, stream: int = 0) -> np.ndarray:
        assert 0 <= stream < self.num_streams
        return self.shards[stream].add_batch(batch) + stream * self.shard_cap

    def reset_stream(self, stream: int) -> None:
        """Seal at a writer identity change (see FrameStackReplay.seal_stream)."""
        if 0 <= stream < self.num_streams:
            self.shards[stream].seal_stream()

    # -- sample --------------------------------------------------------------

    def sample(self, batch_size: int) -> dict[str, np.ndarray]:
        masses = [float(self._sampleable(i)) for i in range(self.num_streams)]
        assert sum(masses) > 0, "sample() before ready()"
        counts = allocate_proportional(batch_size, masses)
        parts = []
        for i, c in enumerate(counts):
            if c == 0:
                continue
            part = self.shards[i].sample(c)
            part["index"] = (part["index"] + i * self.shard_cap).astype(
                np.int32)
            parts.append(part)
        batch = {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}
        batch["_sampled_at"] = tuple(s.steps_added for s in self.shards)
        return batch
