"""Device-resident sequence replay — R2D2 pixels live in HBM.

Closes the last host→device pixel pathology (VERDICT r3 missing #4): the
host ``SequenceReplay`` stores full STACKED observation sequences
(``[cap, T+1, H, W, S]`` uint8 — S× frame duplication from stacking) and
ships ~36 MB of pixels per grad step at batch 64 × 81 × 84×84×4, on a link
where 29 MB measures ~160 ms (replay/device_ring.py docstring). Here:

- Each sequence stores its UNSTACKED frame stream once, in an HBM ring:
  ``W = (stack-1) + (T+1)`` flat rows per sequence (the stack-1 prefix that
  seeds the first observation's stack + one newest frame per step). That is
  a ``stack×``-smaller pixel footprint than the host store, and pixels
  cross the link once, at ingest rate.
- The jitted step gathers the ``[B, T+1, stack]`` window rows per device
  shard and reassembles the stacked observations on device
  (``compose_sequence_rows`` — the sequence twin of
  ``device_ring.compose_stacks``). Reassembly is EXACT: a sequence never
  crosses an episode boundary (``SequenceBuilder`` clears at ``done``), so
  obs[t] is always ``stream[t : t+stack]`` with two masks — pre-episode
  zero padding at the head (``pad`` leading zero frames, from the
  FrameStacker reset semantics) and all-zero rows past the valid length
  (``n_valid``) at the tail, matching the host store's zero padding
  byte-for-byte (tests/test_device_sequence.py).
- Sequence-level metadata (action/reward/discount/mask/carries) and the
  per-sequence PER tree stay host-side — they are KB-scale and the
  priorities come back through the delayed write-back pipeline anyway.

Sharding: sequence slot ``i`` owns ring rows ``[i·W, (i+1)·W)``; slots are
block-partitioned over the ``dp`` mesh axis (shard s holds slots
``[s·caps_local, (s+1)·caps_local)``), writes round-robin across shards,
and ``sample`` draws ``B/D`` sequences per shard concatenated in mesh order
— the same per-shard stratification as ``DeviceFrameReplay``.

Cited reference surface: ``ReplayMemory``-style ``add``/``sample`` [M]
(SURVEY §2), R2D2 semantics per SURVEY §5.7/§7.3 item 3.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_deep_q_tpu.parallel.mesh import AXIS_DP
from distributed_deep_q_tpu.replay.prioritized import SumTree, beta_at, \
    filter_stale


def compose_sequence_rows(ring: jax.Array, seq_local: jax.Array,
                          n_valid: jax.Array,
                          seq_len: int, stack: int) -> jax.Array:
    """Shard-local gather: ``[capL·W, H·W] ring + [b] slots → [b, T+1,
    stack, H·W]`` uint8 rows (flat, gather-natural — the TRAIN program
    reshapes; returning through a transpose here would back-propagate the
    consumer layout onto the ring operand, the measured full-ring relayout
    trap).

    Episode-start FrameStacker padding needs no mask: those stream rows
    are STORED zero, so the gather reproduces the zeros. ``n_valid``
    (real steps in the sequence) drives the tail mask: stacked rows for
    t > n_valid are zeroed wholesale to match the host store's zero tail
    padding exactly (the stream keeps real frames near the seam).
    """
    W = (stack - 1) + (seq_len + 1)
    t = jnp.arange(seq_len + 1)                       # [T+1]
    j = jnp.arange(stack)                             # [stack], oldest first
    # obs[t][..., j] = stream[t + j]
    rel = t[:, None] + j[None, :]                     # [T+1, stack]
    rows = seq_local[:, None, None] * W + rel[None]   # [b, T+1, stack]
    out = ring[rows.reshape(-1)].reshape(rows.shape + (-1,))
    keep = (t[None, :] <= n_valid[:, None])           # [b, T+1]
    return out * keep[..., None, None].astype(jnp.uint8)


def stream_from_stacked_obs(obs: np.ndarray, n_valid: int,
                            stack: int) -> np.ndarray:
    """Host-side inverse of stacking: ``[T+1, H, W, S] → [(S-1)+(T+1),
    H·W]`` newest-frame stream. Row k<S-1 comes from the first
    observation's older stack planes (already zero where the episode
    started inside the stack); row (S-1)+t is obs[t]'s newest plane. Rows
    past ``(S-1)+n_valid`` stay zero, mirroring the host store's tail."""
    t1 = obs.shape[0]
    flat = obs.reshape(t1, -1, obs.shape[-1])         # [T+1, H·W, S]
    W = (stack - 1) + t1
    out = np.zeros((W, flat.shape[1]), np.uint8)
    out[:stack - 1] = np.moveaxis(flat[0, :, :stack - 1], -1, 0)
    n = min(int(n_valid) + 1, t1)                     # real obs rows
    out[stack - 1:stack - 1 + n] = flat[:n, :, -1]
    return out


class DeviceSequenceReplay:
    """Sequence replay with the pixel plane in HBM.

    Host surface mirrors ``SequenceReplay`` (``add_sequence``/``add_batch``
    /``sample``/``update_priorities``/``ready``) so the recurrent loops and
    the RPC server swap it in unchanged; ``sample`` returns sequence-level
    metadata plus per-shard slot indices (``seq_local``, ``pad``,
    ``n_valid``) — the recurrent ring step
    (``SequenceLearner.train_step_from_ring``) composes pixels on device.
    """

    prioritized: bool

    def __init__(
        self,
        capacity: int,
        seq_len: int,
        obs_shape: tuple[int, ...],      # (H, W, S) stacked — pixel only
        mesh: Mesh,
        lstm_size: int = 512,
        prioritized: bool = False,
        alpha: float = 0.9,
        beta0: float = 0.6,
        beta_steps: int = 1_000_000,
        eps: float = 1e-6,
        seed: int = 0,
        use_native: bool = True,
        write_chunk: int = 4,
    ):
        assert len(obs_shape) == 3, \
            "DeviceSequenceReplay is the pixel path: obs_shape = (H, W, S)"
        d = self.num_shards = mesh.shape[AXIS_DP]
        self.mesh = mesh
        self.seq_len = int(seq_len)
        self.stack = int(obs_shape[-1])
        self.frame_shape = tuple(obs_shape[:2])
        self._row_len = int(np.prod(self.frame_shape))
        self.W = (self.stack - 1) + (self.seq_len + 1)  # rows per sequence
        self.caps_local = max(int(capacity) // d, 1)
        self.capacity = self.caps_local * d             # sequences
        t = self.seq_len

        # host metadata (KB-scale), indexed by GLOBAL sequence slot
        cap = self.capacity
        self.action = np.zeros((cap, t), np.int32)
        self.reward = np.zeros((cap, t), np.float32)
        self.discount = np.zeros((cap, t), np.float32)
        self.mask = np.zeros((cap, t), np.float32)
        self.init_c = np.zeros((cap, lstm_size), np.float32)
        self.init_h = np.zeros((cap, lstm_size), np.float32)
        self.n_valid = np.zeros(cap, np.int32)  # real steps (mask sum)
        # per-shard ring cursors/sizes/add-counts (sequence slots)
        self._cursor = np.zeros(d, np.int64)
        self._sizes = np.zeros(d, np.int64)
        self._added = np.zeros(d, np.int64)  # per-shard staleness clock
        self._next_shard = 0
        self._seqs_added = 0
        self._rng = np.random.default_rng(seed)

        self.prioritized = bool(prioritized)
        self.alpha, self.beta0 = float(alpha), float(beta0)
        self.beta_steps, self.eps = int(beta_steps), float(eps)
        self.trees = ([SumTree(self.caps_local, use_native=use_native)
                       for _ in range(d)] if prioritized else None)
        self.max_priority = 1.0
        self._samples = 0

        # HBM stream ring: [capacity·W, H·W] u8, block-sharded over dp
        sharded = NamedSharding(mesh, P(AXIS_DP))
        rows_total = self.capacity * self.W
        self.ring = jax.jit(
            lambda: jnp.zeros((rows_total, self._row_len), jnp.uint8),
            out_shardings=sharded)()

        # donated per-shard scatter, fixed chunk of write_chunk sequences
        self.write_chunk = max(int(write_chunk), 1)
        self._rows_local = self.caps_local * self.W

        def write(ring_local, idx, rows):
            return ring_local.at[idx].set(rows, mode="drop")

        self._write = jax.jit(
            shard_map(write, mesh=mesh,
                      in_specs=(P(AXIS_DP), P(AXIS_DP), P(AXIS_DP)),
                      out_specs=P(AXIS_DP)),
            donate_argnums=0)
        self._pending: list[list[tuple[int, np.ndarray]]] = \
            [[] for _ in range(d)]  # (slot_local, stream rows [W, H·W])

    # -- bookkeeping --------------------------------------------------------

    def __len__(self) -> int:
        return int(self._sizes.sum())

    @property
    def steps_added(self) -> int:
        return self._seqs_added

    def ready(self, learn_start: int) -> bool:
        """Aggregate fill AND every shard sampleable (sample draws B/D
        from each shard — the device_ring per-shard gate)."""
        return (len(self) >= max(learn_start, 1)
                and bool((self._sizes > 0).all()))

    @property
    def beta(self) -> float:
        return beta_at(self._samples, self.beta0, self.beta_steps)

    def _global_slot(self, shard: int, local: int) -> int:
        return shard * self.caps_local + local

    # -- write --------------------------------------------------------------

    def add_sequence(self, seq: dict[str, np.ndarray]) -> int:
        """Standard ``SequenceBuilder`` emission dict (stacked obs): the
        stream derivation happens here, server-side — actors and the RPC
        payload are unchanged."""
        s = self._next_shard
        self._next_shard = (s + 1) % self.num_shards
        local = int(self._cursor[s])
        self._cursor[s] = (local + 1) % self.caps_local
        self._sizes[s] = min(int(self._sizes[s]) + 1, self.caps_local)
        self._added[s] += 1
        g = self._global_slot(s, local)

        n_valid = int(np.asarray(seq["mask"]).sum())
        obs = np.asarray(seq["obs"], np.uint8)
        self.action[g] = seq["action"]
        self.reward[g] = seq["reward"]
        self.discount[g] = seq["discount"]
        self.mask[g] = seq["mask"]
        self.init_c[g] = seq["init_c"]
        self.init_h[g] = seq["init_h"]
        self.n_valid[g] = n_valid
        if self.prioritized:
            self.trees[s].set(
                np.asarray([local]),
                np.asarray([self.max_priority ** self.alpha]))
        self._pending[s].append(
            (local, stream_from_stacked_obs(obs, n_valid, self.stack)))
        self._seqs_added += 1
        if max(len(p) for p in self._pending) >= self.write_chunk:
            self.flush()
        return g

    def add_batch(self, batch: dict[str, np.ndarray]) -> np.ndarray:
        """RPC sequence batches (leading dim = sequence count)."""
        n = len(batch["action"])
        return np.asarray([
            self.add_sequence({k: v[j] for k, v in batch.items()})
            for j in range(n)], np.int64)

    def flush(self) -> None:
        """Scatter staged streams, ``write_chunk`` sequences per shard per
        program (fixed shapes; short shards pad with dropped OOB lanes)."""
        while any(self._pending):
            k, d, W = self.write_chunk, self.num_shards, self.W
            idx = np.full((d, k * W), self._rows_local, np.int32)
            rows = np.zeros((d, k * W, self._row_len), np.uint8)
            for s in range(d):
                for c in range(min(k, len(self._pending[s]))):
                    local, stream = self._pending[s].pop(0)
                    base = local * W
                    idx[s, c * W:(c + 1) * W] = base + np.arange(W)
                    rows[s, c * W:(c + 1) * W] = stream
            self.ring = self._write(self.ring, idx.reshape(-1),
                                    rows.reshape(-1, self._row_len))

    # -- sample -------------------------------------------------------------

    def sample(self, batch_size: int) -> dict[str, np.ndarray]:
        """Index batch: per-shard draws concatenated in mesh order (pixels
        compose on device from ``seq_local``/``pad``/``n_valid``)."""
        self.flush()
        d = self.num_shards
        assert batch_size % d == 0, \
            f"batch {batch_size} must split over {d} shards"
        per = batch_size // d
        self._samples += 1
        locs, weights, gids = [], [], []
        for s in range(d):
            size = int(self._sizes[s])
            assert size > 0, "sample() before ready() on every shard"
            if self.prioritized:
                li = self.trees[s].sample_stratified(per, self._rng)
                li = np.minimum(li, size - 1)
                p = self.trees[s].get(li)
                mass = max(self.trees[s].total, 1e-12)
                # realized stratified draw: P(i) = p_i / (D · mass_s)
                probs = np.maximum(p / (d * mass), 1e-12)
                w = (len(self) * probs) ** (-self.beta)
            else:
                li = self._rng.integers(0, size, size=per)
                w = np.ones(per)
            locs.append(li)
            weights.append(w)
            gids.append(s * self.caps_local + li)
        gidx = np.concatenate(gids)
        w = np.concatenate(weights)
        return {
            "seq_local": np.concatenate(locs).astype(np.int32),
            "n_valid": self.n_valid[gidx],
            "action": self.action[gidx],
            "reward": self.reward[gidx],
            "discount": self.discount[gidx],
            "mask": self.mask[gidx],
            "init_c": self.init_c[gidx],
            "init_h": self.init_h[gidx],
            "weight": (w / w.max()).astype(np.float32),
            "index": gidx.astype(np.int32),
            "_sampled_at": tuple(int(v) for v in self._added),
        }

    # -- learner feedback ---------------------------------------------------

    def update_priorities(self, idx: np.ndarray, priority: np.ndarray,
                          sampled_at: int | None = None) -> None:
        if not self.prioritized:
            return
        gidx = np.asarray(idx, np.int64)
        p = np.abs(np.asarray(priority, np.float64)) + self.eps
        shard, local = gidx // self.caps_local, gidx % self.caps_local
        for s in np.unique(shard):
            pick = shard == s
            li, lp = local[pick], p[pick]
            if sampled_at is not None:
                # per-shard staleness clock: drop updates for slots this
                # shard has overwritten since the sample was drawn
                li, lp = filter_stale(li, lp, int(self._added[s]),
                                      sampled_at[int(s)], self.caps_local)
                if li.size == 0:
                    continue
            self.trees[int(s)].set(li, lp ** self.alpha)
            self.max_priority = max(self.max_priority, float(p.max()))
