"""Device-resident sequence replay — R2D2 pixels, metadata, and
priorities in HBM.

Closes the last host→device pixel pathology (VERDICT r3 missing #4) and,
in round 5, the per-step-dispatch ceiling (VERDICT r4 missing #4): the
host ``SequenceReplay`` stores full STACKED observation sequences
(``[cap, T+1, H, W, S]`` uint8 — S× frame duplication) and ships ~36 MB
of pixels per grad step; the round-4 device ring killed the pixel
transfer but still dispatched one program pair per grad step (~133/s
tunnel ceiling, measured 50.6/s) with per-sequence priorities host-side.

Round-5 design — the sequence twin of ``replay/device_per.py``:

- Each sequence stores its UNSTACKED frame stream once:
  ``W = (stack-1) + (T+1)`` rows (the stack-1 prefix seeding the first
  observation + one newest frame per step), ``stack×`` smaller than the
  host store. The stream lives in ONE flat int32 ring (rows padded to
  the 4 KB DMA tile — ``ops/ring_gather.py``): a sequence is ``W``
  CONTIGUOUS rows, so sampling one sequence is ONE row-DMA and flushing
  one is ONE row-DMA — no gather lowering, no tile amplification (the
  old per-row element gathers read ~230 KB of (32,128) tiles per 7 KB
  row — the measured 20 ms/step).
- Sequence metadata (action/reward/discount/mask/stored carries) and the
  per-sequence priority row live on device too, so ``chain`` grad steps
  run per two-program dispatch (``SequenceLearner`` fused path): the
  host ships per-shard sizes, βs, and sampling keys — nothing reads
  back. Host copies of the metadata are kept for the per-step host
  ``sample()`` path (RPC-server compatibility, priority trees for the
  delayed-write-back pipeline); the two priority planes belong to their
  respective paths and a given training loop drives exactly one.

Sharding: sequence slot ``i`` (shard-local) owns ring rows
``[i·W, (i+1)·W)``; slots are block-partitioned over the ``dp`` mesh
axis, writes round-robin across shards, and sampling draws ``B/D``
sequences per shard concatenated in mesh order — the same per-shard
stratification as ``DeviceFrameReplay``. One scratch sequence slot per
shard absorbs flush padding lanes.

Cited reference surface: ``ReplayMemory``-style ``add``/``sample`` [M]
(SURVEY §2), R2D2 semantics per SURVEY §5.7/§7.3 item 3.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from distributed_deep_q_tpu.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_deep_q_tpu.ops.ring_gather import (
    padded_row_bytes, scatter_rows)
from distributed_deep_q_tpu.parallel.mesh import AXIS_DP
from distributed_deep_q_tpu.replay.prioritized import SumTree, beta_at, \
    filter_stale


def compose_sequence_rows(ring: jax.Array, seq_local: jax.Array,
                          n_valid: jax.Array,
                          seq_len: int, stack: int) -> jax.Array:
    """REFERENCE composition (gather-based, 2-D ``[rows, H·W]`` stream
    store): ``[b]`` slots → ``[b, T+1, stack, H·W]`` uint8 rows. The
    production path DMA-copies each sequence's contiguous row block and
    slices the stacks (``SequenceLearner``); this twin is what tests hold
    it against.

    Episode-start FrameStacker padding needs no mask: those stream rows
    are STORED zero. ``n_valid`` (real steps) drives the tail mask:
    stacked rows for t > n_valid are zeroed wholesale to match the host
    store's zero tail padding exactly.
    """
    W = (stack - 1) + (seq_len + 1)
    t = jnp.arange(seq_len + 1)                       # [T+1]
    j = jnp.arange(stack)                             # [stack], oldest first
    # obs[t][..., j] = stream[t + j]
    rel = t[:, None] + j[None, :]                     # [T+1, stack]
    rows = seq_local[:, None, None] * W + rel[None]   # [b, T+1, stack]
    out = ring[rows.reshape(-1)].reshape(rows.shape + (-1,))
    keep = (t[None, :] <= n_valid[:, None])           # [b, T+1]
    return out * keep[..., None, None].astype(jnp.uint8)


def compose_sequence_block(block: jax.Array, mask: jax.Array,
                           seq_len: int, stack: int,
                           row_len: int) -> jax.Array:
    """PRODUCTION composition: one sequence's DMA'd contiguous row block
    ``[b, W, rowp]`` int32 → ``[b, T+1, stack, row_len]`` uint8 via
    ``stack`` STATIC slices (obs[t] plane j = stream row t+j) — no
    gathers anywhere. ``mask`` [b, T] drives the tail zeroing
    (n_valid = Σ mask, matching the host store's zero tail)."""
    from jax import lax

    b, W, rowp = block.shape
    pix = lax.bitcast_convert_type(block, jnp.uint8)
    pix = pix.reshape(b, W, rowp * 4)[:, :, :row_len]
    obs = jnp.stack([pix[:, j:j + seq_len + 1] for j in range(stack)],
                    axis=2)                            # [b, T+1, stack, row]
    n_valid = jnp.sum(mask, axis=1).astype(jnp.int32)  # [b]
    keep = jnp.arange(seq_len + 1)[None, :] <= n_valid[:, None]
    return obs * keep[..., None, None].astype(jnp.uint8)


def stream_from_stacked_obs(obs: np.ndarray, n_valid: int,
                            stack: int) -> np.ndarray:
    """Host-side inverse of stacking: ``[T+1, H, W, S] → [(S-1)+(T+1),
    H·W]`` newest-frame stream. Row k<S-1 comes from the first
    observation's older stack planes (already zero where the episode
    started inside the stack); row (S-1)+t is obs[t]'s newest plane. Rows
    past ``(S-1)+n_valid`` stay zero, mirroring the host store's tail."""
    t1 = obs.shape[0]
    flat = obs.reshape(t1, -1, obs.shape[-1])         # [T+1, H·W, S]
    W = (stack - 1) + t1
    out = np.zeros((W, flat.shape[1]), np.uint8)
    out[:stack - 1] = np.moveaxis(flat[0, :, :stack - 1], -1, 0)
    n = min(int(n_valid) + 1, t1)                     # real obs rows
    out[stack - 1:stack - 1 + n] = flat[:n, :, -1]
    return out


class DeviceSequenceReplay:
    """Sequence replay with pixels, metadata, and priorities in HBM.

    Host surface mirrors ``SequenceReplay`` (``add_sequence``/``add_batch``
    /``sample``/``update_priorities``/``ready``) so the recurrent loops and
    the RPC server swap it in unchanged; ``sample`` returns sequence-level
    metadata plus per-shard slot indices for the per-step ring path, and
    the fused chained path (``SequenceSolver.train_steps_device_per``)
    never calls it — it samples on device from ``dmeta``.
    """

    prioritized: bool

    def __init__(
        self,
        capacity: int,
        seq_len: int,
        obs_shape: tuple[int, ...],      # (H, W, S) stacked — pixel only
        mesh: Mesh,
        lstm_size: int = 512,
        prioritized: bool = False,
        alpha: float = 0.9,
        beta0: float = 0.6,
        beta_steps: int = 1_000_000,
        eps: float = 1e-6,
        seed: int = 0,
        use_native: bool = True,
        write_chunk: int = 4,
    ):
        assert len(obs_shape) == 3, \
            "DeviceSequenceReplay is the pixel path: obs_shape = (H, W, S)"
        d = self.num_shards = mesh.shape[AXIS_DP]
        self.mesh = mesh
        # multi-controller topology (mirrors DevicePERFrameReplay): this
        # process writes only the shards its devices host; flushes become
        # lockstep collectives with a MAX-agreed round count, and planes
        # assemble per-process local blocks into the global arrays
        self._pc = jax.process_count()
        self._pid = jax.process_index()
        self.local_shards = [s for s, dev in enumerate(mesh.devices.flat)
                             if dev.process_index == self._pid]
        assert self.local_shards == list(range(
            self.local_shards[0],
            self.local_shards[0] + len(self.local_shards))), (
            "mesh device order must group each process's shards "
            "contiguously for P('dp') local-block assembly")
        self.defer_flush = self._pc > 1
        self.seq_len = int(seq_len)
        self.stack = int(obs_shape[-1])
        self.frame_shape = tuple(obs_shape[:2])
        self._row_len = int(np.prod(self.frame_shape))
        self.W = (self.stack - 1) + (self.seq_len + 1)  # rows per sequence
        self.caps_local = max(int(capacity) // d, 1)
        self.capacity = self.caps_local * d             # sequences
        self.lstm_size = int(lstm_size)
        t = self.seq_len

        # host metadata (KB-scale), indexed by GLOBAL sequence slot — the
        # per-step host sample path reads these; the fused path reads the
        # device twins below
        cap = self.capacity
        self.action = np.zeros((cap, t), np.int32)
        self.reward = np.zeros((cap, t), np.float32)
        self.discount = np.zeros((cap, t), np.float32)
        self.mask = np.zeros((cap, t), np.float32)
        self.init_c = np.zeros((cap, lstm_size), np.float32)
        self.init_h = np.zeros((cap, lstm_size), np.float32)
        self.n_valid = np.zeros(cap, np.int32)  # real steps (mask sum)
        # per-shard ring cursors/sizes/add-counts (sequence slots)
        self._cursor = np.zeros(d, np.int64)
        self._sizes = np.zeros(d, np.int64)
        self._added = np.zeros(d, np.int64)  # per-shard staleness clock
        self._next_shard = 0
        self._seqs_added = 0
        self._rng = np.random.default_rng(seed)

        self.prioritized = bool(prioritized)
        self.alpha, self.beta0 = float(alpha), float(beta0)
        self.beta_steps, self.eps = int(beta_steps), float(eps)
        self.trees = ([SumTree(self.caps_local, use_native=use_native)
                       for _ in range(d)] if prioritized else None)
        self.max_priority = 1.0
        self._samples = 0

        # flat padded int32 pixel ring (ops/ring_gather.py layout): one
        # scratch sequence slot per shard absorbs flush padding lanes
        assert write_chunk <= self.caps_local, (
            "write_chunk sequences must fit one shard ring (duplicate "
            "scatter targets within a flush chunk are forbidden)")
        self.rowb = padded_row_bytes(self._row_len)
        self.rowp = self.rowb // 4
        self.seq_elems = self.W * self.rowp
        self.slots_local = self.caps_local + 1
        assert self.slots_local * self.seq_elems < 2**31, (
            "per-shard sequence plane exceeds Mosaic's 32-bit index range "
            "— shard over more devices or shrink capacity/seq_len")
        self._interpret = mesh.devices.flat[0].platform == "cpu"
        sharded = NamedSharding(mesh, P(AXIS_DP))
        replicated = NamedSharding(mesh, P())
        self.ring = jax.jit(
            lambda: jnp.zeros(d * self.slots_local * self.seq_elems,
                              jnp.int32),
            out_shardings=sharded)()

        # device metadata/priority twins (fused chained path)
        def init_meta():
            return {
                "action": jnp.zeros((cap, t), jnp.int32),
                "reward": jnp.zeros((cap, t), jnp.float32),
                "discount": jnp.zeros((cap, t), jnp.float32),
                "mask": jnp.zeros((cap, t), jnp.float32),
                "init_c": jnp.zeros((cap, lstm_size), jnp.float32),
                "init_h": jnp.zeros((cap, lstm_size), jnp.float32),
                "prio": jnp.zeros(cap, jnp.float32),
            }

        self.dmeta = jax.jit(
            init_meta, out_shardings={k: sharded for k in (
                "action", "reward", "discount", "mask", "init_c",
                "init_h", "prio")})()
        self.dmaxp = jax.device_put(jnp.ones((), jnp.float32), replicated)

        # fused meta-scatter + pixel-DMA writer, fixed chunk of
        # write_chunk sequences per shard per program
        self.write_chunk = k = max(int(write_chunk), 1)
        alpha_w = self.alpha
        seq_bytes = self.W * self.rowb
        interpret = self._interpret

        def write(ring, meta, maxp, idx, act, rew, disc, msk, ic, ih,
                  sidx, didx, staged):
            new_p = maxp ** alpha_w
            ring = scatter_rows(sidx, didx, staged, ring, n=k,
                                rowb=seq_bytes, interpret=interpret)
            meta = {
                "action": meta["action"].at[idx].set(act, mode="drop"),
                "reward": meta["reward"].at[idx].set(rew, mode="drop"),
                "discount": meta["discount"].at[idx].set(disc,
                                                         mode="drop"),
                "mask": meta["mask"].at[idx].set(msk, mode="drop"),
                "init_c": meta["init_c"].at[idx].set(ic, mode="drop"),
                "init_h": meta["init_h"].at[idx].set(ih, mode="drop"),
                "prio": meta["prio"].at[idx].set(new_p, mode="drop"),
            }
            return ring, meta

        S = P(AXIS_DP)
        meta_spec = {key: S for key in self.dmeta}
        self._write = jax.jit(
            shard_map(write, mesh=mesh,
                      in_specs=(S, meta_spec, P()) + (S,) * 10,
                      out_specs=(S, meta_spec), check_vma=False),
            donate_argnums=(0, 1))
        self._pending: list[list[tuple]] = [[] for _ in range(d)]

    # -- bookkeeping --------------------------------------------------------

    def __len__(self) -> int:
        return int(self._sizes.sum())

    @property
    def steps_added(self) -> int:
        return self._seqs_added

    def pending_rows(self) -> int:
        return sum(len(p) for p in self._pending)

    def ready(self, learn_start: int) -> bool:
        """Aggregate fill AND every LOCAL shard sampleable (sample draws
        B/D from each shard; multi-host the cross-process AND happens at
        the caller via all_processes_ready)."""
        return (len(self) >= max(learn_start, 1)
                and bool((self._sizes[self.local_shards] > 0).all()))

    @property
    def beta(self) -> float:
        return beta_at(self._samples, self.beta0, self.beta_steps)

    def next_betas(self, n: int) -> np.ndarray:
        """β for the next ``n`` fused steps (anneal advances before each
        read — host-path ordering)."""
        out = np.empty(n, np.float32)
        for i in range(n):
            self._samples += 1
            out[i] = self.beta
        return out

    def device_inputs(self) -> np.ndarray:
        """This process's LOCAL shards' filled-slot counts [dl] int32 for
        the fused sampler (the local block of the global P('dp') plane —
        single-process that IS the whole plane)."""
        return self._sizes[self.local_shards].astype(np.int32)

    def to_replicated(self, arr: np.ndarray):
        """Replicate a host value onto the (possibly multi-host) mesh."""
        if self._pc == 1:
            return arr
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.make_array_from_process_local_data(
            NamedSharding(self.mesh, P()), np.ascontiguousarray(arr),
            global_shape=arr.shape)

    def _global_slot(self, shard: int, local: int) -> int:
        return shard * self.caps_local + local

    # -- write --------------------------------------------------------------

    def add_sequence(self, seq: dict[str, np.ndarray]) -> int:
        """Standard ``SequenceBuilder`` emission dict (stacked obs): the
        stream derivation happens here, server-side — actors and the RPC
        payload are unchanged. Writes round-robin across this process's
        LOCAL shards (all shards, single-process)."""
        s = self.local_shards[self._next_shard % len(self.local_shards)]
        self._next_shard += 1
        local = int(self._cursor[s])
        self._cursor[s] = (local + 1) % self.caps_local
        self._sizes[s] = min(int(self._sizes[s]) + 1, self.caps_local)
        self._added[s] += 1
        g = self._global_slot(s, local)

        n_valid = int(np.asarray(seq["mask"]).sum())
        obs = np.asarray(seq["obs"], np.uint8)
        self.action[g] = seq["action"]
        self.reward[g] = seq["reward"]
        self.discount[g] = seq["discount"]
        self.mask[g] = seq["mask"]
        self.init_c[g] = seq["init_c"]
        self.init_h[g] = seq["init_h"]
        self.n_valid[g] = n_valid
        if self.prioritized:
            self.trees[s].set(
                np.asarray([local]),
                np.asarray([self.max_priority ** self.alpha]))
        stream = stream_from_stacked_obs(obs, n_valid, self.stack)
        padded = np.zeros((self.W, self.rowb), np.uint8)
        padded[:, :self._row_len] = stream
        self._pending[s].append((local, padded, self.action[g],
                                 self.reward[g], self.discount[g],
                                 self.mask[g], self.init_c[g],
                                 self.init_h[g]))
        self._seqs_added += 1
        if max(len(p) for p in self._pending) >= self.write_chunk \
                and not self.defer_flush:
            self.flush()
        return g

    def add_batch(self, batch: dict[str, np.ndarray]) -> np.ndarray:
        """RPC sequence batches (leading dim = sequence count)."""
        n = len(batch["action"])
        return np.asarray([
            self.add_sequence({k: v[j] for k, v in batch.items()})
            for j in range(n)], np.int64)

    def to_global(self, local: np.ndarray):
        """Assemble this process's contiguous local block (dim 0) of a
        ``P('dp')`` plane into the global array; identity single-process."""
        if self._pc == 1:
            return local
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = P(*((AXIS_DP,) + (None,) * (local.ndim - 1)))
        factor = self.num_shards // len(self.local_shards)
        return jax.make_array_from_process_local_data(
            NamedSharding(self.mesh, spec), np.ascontiguousarray(local),
            global_shape=(local.shape[0] * factor,) + local.shape[1:])

    def flush(self) -> None:
        """Push staged sequences to HBM, ``write_chunk`` per LOCAL shard
        per program: ONE row-DMA per sequence (contiguous W-row block) +
        the metadata scatters; short shards pad with scratch-slot lanes.
        Multi-host: the round count is MAX-agreed across processes (the
        write is a global-array collective every process must enter
        equally; short hosts send all-padding chunks), so every process
        must call flush() at the same loop point — the fused dispatch
        path does, and ingest defers via ``defer_flush``."""
        rounds = -(-max((len(self._pending[s]) for s in self.local_shards),
                        default=0) // self.write_chunk)
        if self._pc > 1:
            from distributed_deep_q_tpu.parallel.multihost import (
                global_max_int)
            rounds = global_max_int(rounds)
        for _ in range(rounds):
            k, t = self.write_chunk, self.seq_len
            dl = len(self.local_shards)
            idx = np.full((dl, k), self.caps_local, np.int32)  # scratch
            staged = np.zeros((dl, k, self.W, self.rowb), np.uint8)
            act = np.zeros((dl, k, t), np.int32)
            rew = np.zeros((dl, k, t), np.float32)
            disc = np.zeros((dl, k, t), np.float32)
            msk = np.zeros((dl, k, t), np.float32)
            ic = np.zeros((dl, k, self.lstm_size), np.float32)
            ih = np.zeros((dl, k, self.lstm_size), np.float32)
            for li, s in enumerate(self.local_shards):
                for c in range(min(k, len(self._pending[s]))):
                    (local, stream, a, r, dc, m, c0, h0) = \
                        self._pending[s].pop(0)
                    idx[li, c] = local
                    staged[li, c] = stream
                    act[li, c], rew[li, c], disc[li, c] = a, r, dc
                    msk[li, c], ic[li, c], ih[li, c] = m, c0, h0
            src = np.tile(np.arange(k, dtype=np.int32), (dl, 1))
            g = self.to_global
            self.ring, self.dmeta = self._write(
                self.ring, self.dmeta, self.dmaxp,
                g(idx.reshape(-1)), g(act.reshape(dl * k, t)),
                g(rew.reshape(dl * k, t)), g(disc.reshape(dl * k, t)),
                g(msk.reshape(dl * k, t)), g(ic.reshape(dl * k, -1)),
                g(ih.reshape(dl * k, -1)), g(src.reshape(-1)),
                g(idx.reshape(-1)),
                g(staged.reshape(dl, -1).view(np.int32).reshape(-1)))

    # -- sample (per-step host path) ----------------------------------------

    def sample(self, batch_size: int) -> dict[str, np.ndarray]:
        """Index batch: per-shard draws concatenated in mesh order (pixels
        compose on device from ``seq_local``/``n_valid``)."""
        self.flush()
        d = self.num_shards
        assert batch_size % d == 0, \
            f"batch {batch_size} must split over {d} shards"
        per = batch_size // d
        self._samples += 1
        locs, weights, gids = [], [], []
        for s in range(d):
            size = int(self._sizes[s])
            assert size > 0, "sample() before ready() on every shard"
            if self.prioritized:
                li = self.trees[s].sample_stratified(per, self._rng)
                li = np.minimum(li, size - 1)
                p = self.trees[s].get(li)
                mass = max(self.trees[s].total, 1e-12)
                # realized stratified draw: P(i) = p_i / (D · mass_s)
                probs = np.maximum(p / (d * mass), 1e-12)
                w = (len(self) * probs) ** (-self.beta)
            else:
                li = self._rng.integers(0, size, size=per)
                w = np.ones(per)
            locs.append(li)
            weights.append(w)
            gids.append(s * self.caps_local + li)
        gidx = np.concatenate(gids)
        w = np.concatenate(weights)
        return {
            "seq_local": np.concatenate(locs).astype(np.int32),
            "n_valid": self.n_valid[gidx],
            "action": self.action[gidx],
            "reward": self.reward[gidx],
            "discount": self.discount[gidx],
            "mask": self.mask[gidx],
            "init_c": self.init_c[gidx],
            "init_h": self.init_h[gidx],
            "weight": (w / w.max()).astype(np.float32),
            "index": gidx.astype(np.int32),
            "_sampled_at": tuple(int(v) for v in self._added),
        }

    # -- learner feedback ---------------------------------------------------

    def update_priorities(self, idx: np.ndarray, priority: np.ndarray,
                          sampled_at: int | None = None) -> None:
        if not self.prioritized:
            return
        gidx = np.asarray(idx, np.int64)
        p = np.abs(np.asarray(priority, np.float64)) + self.eps
        shard, local = gidx // self.caps_local, gidx % self.caps_local
        for s in np.unique(shard):
            pick = shard == s
            li, lp = local[pick], p[pick]
            if sampled_at is not None:
                # per-shard staleness clock: drop updates for slots this
                # shard has overwritten since the sample was drawn
                li, lp = filter_stale(li, lp, int(self._added[s]),
                                      sampled_at[int(s)], self.caps_local)
                if li.size == 0:
                    continue
            self.trees[int(s)].set(li, lp ** self.alpha)
            self.max_priority = max(self.max_priority, float(p.max()))
