"""Device-resident prioritized replay — sampling fused INTO the train step.

The host-PER data path (``replay/prioritized.py`` sum-tree + index batches)
pays two host round trips per grad step: the sampled-index upload and the
per-sample |TD| readback for priority updates. On a tunneled/remote TPU
runtime the readback alone measures ~70 ms (bench.py), and even the
host-side sum-tree walk (~1.3 ms at batch 512 over a 1M ring) bounds the
learner. This module moves the WHOLE prioritized loop into HBM
(SURVEY §7.3 item 2, redesigned TPU-first instead of host-first):

- per-row metadata rings (action, reward, done, boundary) and a priority
  row ``p^α`` live on device, sharded ``P('dp')`` exactly like the frame
  ring; the flush scatter writes all of them in one program, with fresh
  rows initialized to the running max-priority device scalar.
- each train step, per shard: build the validity mask from the (tiny,
  host-shipped) per-slot cursors/sizes, draw ``B/D`` indices by inverse-CDF
  over the masked priorities (``cumsum`` + ``searchsorted`` — the sum-tree's
  job, done as one memory-bound pass at HBM bandwidth), compose frame
  stacks and n-step returns from the device rings, compute IS weights
  (stratified-realized form, matching ``DeviceFrameReplay.sample``), run
  the DQN step, and scatter ``(|TD|+ε)^α`` straight back into the priority
  row — zero-step-stale, no D2H anywhere.

The per-device layout mirrors ``device_ring.py``: a shard holds
``subs_per_shard`` sub-rings (slots) of ``slot_cap`` rows; all mask/window
math reshapes ``[cap_local] → [subs, slot_cap]`` so ring wraps stay inside
a sub-ring. Host-side slot bookkeeping (cursors/sizes/boundaries) is
unchanged — the device copies exist so composition never needs the host.
"""

from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from distributed_deep_q_tpu.replay.device_ring import DeviceFrameReplay


class DeviceReplayState(flax.struct.PyTreeNode):
    """Device twin of the replay ring: pixels + metadata + priorities.

    All arrays are global (mesh-sharded over their leading axis); ``maxp``
    is the replicated running max |TD| priority (pre-α), used to seed
    fresh rows optimistically.
    """

    frames: jax.Array     # [capacity, H·W] uint8
    action: jax.Array     # [capacity] int32
    reward: jax.Array     # [capacity] float32
    done: jax.Array       # [capacity] uint8 (cuts bootstrap)
    boundary: jax.Array   # [capacity] uint8 (any episode end)
    prio: jax.Array       # [capacity] float32, p^α (0 = never written)
    maxp: jax.Array       # [] float32, running max pre-α priority


def valid_mask(done: jax.Array, boundary: jax.Array, cursors: jax.Array,
               sizes: jax.Array, slot_cap: int, stack: int,
               n_step: int) -> jax.Array:
    """Per-row sampleability for one shard — device twin of
    ``FrameStackReplay._invalid`` vectorized over the shard's sub-rings.

    ``done``/``boundary`` are the shard's rows ``[cap_local]``; ``cursors``
    and ``sizes`` are ``[subs]`` per-sub write cursors / fill counts. A row
    is sampleable iff its ``[i-stack+1, i+n]`` window neither crosses the
    write cursor nor falls off the filled region, and its n-step window
    crosses no truncation-only boundary.
    """
    L = slot_cap
    d = done.reshape(-1, L).astype(bool)
    b = boundary.reshape(-1, L).astype(bool)
    subs = d.shape[0]
    idx = jnp.arange(L)[None, :]                        # [1, L]
    size = sizes[:, None]                               # [subs, 1]
    cur = cursors[:, None]
    partial = (idx < stack - 1) | (idx + n_step >= size)
    back = (idx - cur) % L
    full = (back >= L - n_step) | (back < stack - 1)
    bad = jnp.where(size < L, partial, full)
    trunc = b & ~d
    cross = jnp.zeros((subs, L), bool)
    for k in range(n_step):
        cross = cross | jnp.roll(trunc, -k, axis=1)
    return (~(bad | cross)).reshape(-1)                 # [cap_local]


def build_cdf(prio_masked: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(inclusive CDF, total mass) over a shard's masked priorities. ONE
    ``cumsum`` over the shard (memory-bound, HBM rate) replaces the host
    sum-tree descent. Capacity-scaled (O(cap_local) passes) — so the
    chained path builds it ONCE per chunk: sampling is defined against
    the priorities as of chunk start, making the CDF scan-invariant (the
    in-scan version cost ~1.7 ms/step extra at 1M rows, measured)."""
    cdf = jnp.cumsum(prio_masked)
    return cdf, cdf[-1]


def draw_from_cdf(key: jax.Array, cdf: jax.Array, prio_masked: jax.Array,
                  mass: jax.Array, num: int,
                  ) -> tuple[jax.Array, jax.Array]:
    """``num`` inverse-CDF draws ∝ p: (indices [num], p_i/mass [num]).
    [B]-scale only — safe inside a scan."""
    u = jax.random.uniform(key, (num,)) * mass
    idx = jnp.searchsorted(cdf, u, side="right")
    idx = jnp.clip(idx, 0, prio_masked.shape[0] - 1)
    p = prio_masked[idx] / jnp.maximum(mass, 1e-12)
    return idx, p


def sample_from_cdf(key: jax.Array, prio_masked: jax.Array,
                    num: int) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Build + draw in one call (single-step convenience). Returns
    (indices [num], probabilities p_i/mass [num], mass [])."""
    cdf, mass = build_cdf(prio_masked)
    idx, p = draw_from_cdf(key, cdf, prio_masked, mass, num)
    return idx, p, mass


def _stack_window(boundary: jax.Array, local: jax.Array, sub: jax.Array,
                  slot_cap: int, stack: int) -> tuple[jax.Array, jax.Array]:
    """(shard-local frame indices [B, stack] oldest-first, validity mask) —
    device twin of ``FrameStackReplay._stack_indices``."""
    L = slot_cap
    offs = jnp.arange(stack - 1, -1, -1)                # stack-1 .. 0
    loc = (local[:, None] - offs[None, :]) % L          # [B, stack]
    flat = sub[:, None] * L + loc
    prev_b = boundary[sub[:, None] * L + (loc - 1) % L].astype(bool)
    # valid right-to-left, unrolled (stack is tiny and static): newest
    # frame always valid, older frames valid while no boundary sits
    # between them and the anchor
    valid_cols = [jnp.ones(local.shape[0], bool)]
    for j in range(stack - 2, -1, -1):
        valid_cols.append(valid_cols[-1] & ~prev_b[:, j + 1])
    valid = jnp.stack(valid_cols[::-1], axis=1)         # [B, stack]
    return flat.astype(jnp.int32), valid


def stack_rows_to_obs(rows: jax.Array,
                      frame_shape: tuple[int, int]) -> jax.Array:
    """[B, stack, H·W] gathered rows → [B, H, W, stack] CNN input.

    Kept OUT of the sampling program on purpose: the transpose propagates
    the consumer's preferred layout backwards onto the frame-ring gather
    operand during XLA layout assignment, which materializes a relayout
    copy of the ENTIRE ring per step (7 GB at 1M capacity, ~29 ms
    measured). The sampling program returns gather-natural flat rows; the
    train program does this (14 MB) rearrangement instead.
    """
    rows = rows.reshape(rows.shape[:2] + tuple(frame_shape))
    return jnp.moveaxis(rows, 1, -1)


def gather_rows(frames: jax.Array, flat_idx: jax.Array,
                valid: jax.Array) -> jax.Array:
    """``frames[flat_idx]`` with invalid stack positions zeroed — the ONE
    place the pixel plane is touched. Kept OUT of any ``lax.scan``: a
    gather inside a scan body makes XLA materialize a ring-sized temp per
    iteration (measured: the compiled chained sample program carried a
    471 MB temp ≈ one full 462 MB ring copy per step, ~2.5 ms/step at
    batch 512 vs ~0.04 ms of actual gathered bytes). Batched over the
    chunk, the leading dims of ``flat_idx`` are free."""
    f = frames[flat_idx.reshape(-1)].reshape(flat_idx.shape + (-1,))
    return f * valid[..., None].astype(jnp.uint8)


def compose_meta(state_rows: dict[str, jax.Array], local: jax.Array,
                 sub: jax.Array, slot_cap: int, stack: int,
                 n_step: int, gamma: float):
    """Device twin of ``FrameStackReplay.gather_meta``: from sampled
    (sub, local) rows build the n-step return, bootstrap discount, action,
    and the obs/next_obs WINDOW INDICES + validity masks (the pixel gather
    itself happens outside, ``gather_rows``). Returns
    (meta dict, oflat, ovalid, nflat, nvalid)."""
    L = slot_cap
    action = state_rows["action"]
    reward, done, boundary = (state_rows["reward"], state_rows["done"],
                              state_rows["boundary"])

    oflat, ovalid = _stack_window(boundary, local, sub, L, stack)
    nflat, nvalid = _stack_window(boundary, (local + n_step) % L, sub, L,
                                  stack)
    ks = jnp.arange(n_step)
    win = sub[:, None] * L + (local[:, None] + ks[None, :]) % L  # [B, n]
    d = done[win].astype(bool)
    continuing = jnp.ones(d.shape, bool)
    if n_step > 1:
        continuing = continuing.at[:, 1:].set(
            ~jnp.cumsum(d[:, :-1], axis=1).astype(bool))
    gammas = gamma ** jnp.arange(n_step + 1, dtype=jnp.float32)
    r = (reward[win] * continuing * gammas[None, :n_step]).sum(axis=1)
    any_done = (d & continuing).any(axis=1)
    discount = jnp.where(any_done, 0.0, gammas[n_step]).astype(jnp.float32)
    flat = sub * L + local
    meta = {
        "action": action[flat],
        "reward": r.astype(jnp.float32),
        "discount": discount,
    }
    return meta, oflat, ovalid, nflat, nvalid


def compose_from_state(state_rows: dict[str, jax.Array], local: jax.Array,
                       sub: jax.Array, slot_cap: int, stack: int,
                       n_step: int, gamma: float) -> dict[str, jax.Array]:
    """Meta composition + the pixel gather in one call — the single-step
    (unchained) convenience wrapper over ``compose_meta``/``gather_rows``.
    """
    meta, oflat, ovalid, nflat, nvalid = compose_meta(
        state_rows, local, sub, slot_cap, stack, n_step, gamma)
    return {
        **meta,
        "obs_rows": gather_rows(state_rows["frames"], oflat, ovalid),
        "nobs_rows": gather_rows(state_rows["frames"], nflat, nvalid),
    }


def fused_sample_prep(shard_rows: dict[str, jax.Array],
                      cursors: jax.Array, sizes: jax.Array,
                      slot_cap: int, stack: int, n_step: int):
    """The CAPACITY-SCALED part of a fused prioritized sample, built once
    per chunk (scan-invariant: the chained path samples against the
    priorities as of chunk start): validity mask → masked priorities →
    CDF/mass → global sampleable count. Returns (pm, cdf, mass, n_glob).
    """
    from jax import lax

    mask = valid_mask(shard_rows["done"], shard_rows["boundary"], cursors,
                      sizes, slot_cap, stack, n_step)
    pm = shard_rows["prio"] * mask
    cdf, mass = build_cdf(pm)
    n_glob = lax.psum(jnp.sum(mask.astype(jnp.float32)), "dp")
    return pm, cdf, mass, n_glob


def fused_sample_draw(key: jax.Array, shard_rows: dict[str, jax.Array],
                      pm: jax.Array, cdf: jax.Array, mass: jax.Array,
                      n_glob: jax.Array, per_shard: int, slot_cap: int,
                      stack: int, n_step: int, gamma: float,
                      beta: jax.Array, num_shards: int):
    """One step's [B]-scale fused prioritized sample: CDF draw → meta
    composition → IS weights; ``fused_sample_draw_many`` at chain=1.

    REFERENCE implementation, not the production path: the learner runs
    ``fused_sample_draw_packed`` (pack row-gathers + window DMA); this
    gather-based twin is the executable spec the packed path is tested
    against (tests/test_device_per.py equivalence test) and what the
    zero-mass/uniformity unit tests drive directly."""
    batch, oflat, ovalid, nflat, nvalid, idx = fused_sample_draw_many(
        key[None], shard_rows, pm, cdf, mass, n_glob, per_shard, slot_cap,
        stack, n_step, gamma, jnp.asarray(beta)[None], num_shards)
    batch = {k: v[0] for k, v in batch.items()}
    return (batch, oflat[0], ovalid[0], nflat[0], nvalid[0], idx[0])


def fused_sample_draw_many(keys: jax.Array,
                           shard_rows: dict[str, jax.Array],
                           pm: jax.Array, cdf: jax.Array, mass: jax.Array,
                           n_glob: jax.Array, per_shard: int, slot_cap: int,
                           stack: int, n_step: int, gamma: float,
                           betas: jax.Array, num_shards: int):
    """All ``chain`` draws of a chunk in one straight-line vectorized
    block (no scan: the draw has no carry — sampling is defined against
    chunk-start priorities — and scanned bodies re-touch capacity-sized
    operands per iteration).

    REFERENCE twin of the production ``fused_sample_draw_packed``: this
    composes meta through ``compose_meta``'s window gathers (clear,
    tile-amplified); the packed path composes the same values from
    ``build_meta_pack`` row lanes. The equivalence test in
    tests/test_device_per.py holds the two together.

    Per-step key semantics: row i draws ``uniform(keys[i], (per_shard,))``
    — the vmap computes the same Threefry bits as ``chain`` separate
    calls, so a chain=k chunk byte-matches k single-step dispatches
    (``test_chained_fused_steps_match_sequential_alpha0``).

    ``keys`` is [chain, 2] uint32, ``betas`` [chain]. Returns the same
    tuple as ``fused_sample_draw`` with a leading [chain] axis everywhere.
    """
    from jax import lax

    chain = keys.shape[0]
    idx, p = jax.vmap(
        lambda k: draw_from_cdf(k, cdf, pm, mass, per_shard))(keys)
    sub, local = idx // slot_cap, idx % slot_cap
    meta, oflat, ovalid, nflat, nvalid = compose_meta(
        shard_rows, local.reshape(-1), sub.reshape(-1), slot_cap, stack,
        n_step, gamma)
    lead = (chain, per_shard)
    meta = {k: v.reshape(lead + v.shape[1:]) for k, v in meta.items()}
    oflat, ovalid, nflat, nvalid = (
        x.reshape(lead + x.shape[1:])
        for x in (oflat, ovalid, nflat, nvalid))
    meta["weight"] = stratified_is_weights(p, mass, n_glob, betas,
                                           num_shards)
    idx = jnp.where(mass > 0, idx, pm.shape[0])
    return meta, oflat, ovalid, nflat, nvalid, idx.astype(jnp.int32)


def stratified_is_weights(p: jax.Array, mass: jax.Array,
                          n_glob: jax.Array, betas: jax.Array,
                          num_shards: int) -> jax.Array:
    """IS weights for the realized per-shard stratified draw, normalized
    per chain row — THE single copy of this math, shared by the
    transition samplers (reference and packed) and the fused sequence
    sampler. ``p`` [chain, B] draw probabilities (p_i/mass),
    ``betas`` [chain]; runs inside shard_map (``lax.pmax`` over 'dp').

    P(i) = p_i/(D·mass_s) — each shard contributes exactly B/D draws,
    matching the host path's weight math; N = global sampleable count
    (``n_glob``, psum'd once per chunk).

    A shard whose masked priority mass is zero (e.g. its only sampleable
    slot sealed away post-warmup) would otherwise compose garbage rows
    with extreme weights: zero those weights (the caller points its
    priority scatter out of bounds), so the degenerate shard contributes
    nothing — the host path raises instead; here the step stays total.
    Masking must precede the pmax: a dead shard's floored p=1e-12 blows
    w up to ~1e4, and normalizing live shards by THAT w_max would crush
    the whole batch's learning signal."""
    from jax import lax

    pr = jnp.maximum(p / num_shards, 1e-12)
    w = (n_glob * pr) ** (-betas[:, None])
    w = jnp.where(mass > 0, w, 0.0)
    w_max = lax.pmax(jnp.max(w, axis=1), "dp")             # [chain]
    return (w / jnp.maximum(w_max[:, None], 1e-12)).astype(jnp.float32)


def build_meta_pack(action: jax.Array, reward: jax.Array, done: jax.Array,
                    boundary: jax.Array, slot_cap: int, stack: int,
                    n_step: int, gamma: float) -> jax.Array:
    """Per-row composed sample metadata for ALL rows at once — the roll
    twin of ``compose_meta``. Returns ``[cap_local, 3 + stack]`` float32:
    lane 0 action, 1 n-step return, 2 bootstrap discount, 3.. the obs
    stack-validity bits of the row as anchor (oldest-first).

    Why: per-sample element gathers from the [cap_local] metadata rows
    read a full (8,128)/(32,128) tile per element on TPU — measured
    ~42 ms per 32-step chunk at 1M capacity (scripts/sample_ablate.py).
    Rolls compose the same windows for every row in a handful of
    sequential passes at HBM bandwidth, and the sampler then needs just
    TWO row gathers per sample (anchor and anchor+n) from this pack.
    ``jnp.roll`` wraps within each sub-ring after the ``[subs, L]``
    reshape — exactly the mod-``L`` window math of ``compose_meta``.
    """
    L = slot_cap
    a2 = action.reshape(-1, L).astype(jnp.float32)
    r2 = reward.reshape(-1, L).astype(jnp.float32)
    d2 = done.reshape(-1, L).astype(bool)
    b2 = boundary.reshape(-1, L).astype(bool)
    # n-step return / discount: row i's window rows are roll(-k)[i]
    rn = r2
    any_done = d2
    cont = ~d2
    for k in range(1, n_step):
        dk = jnp.roll(d2, -k, axis=1)
        rn = rn + jnp.roll(r2, -k, axis=1) * cont * (gamma ** k)
        any_done = any_done | (dk & cont)
        cont = cont & ~dk
    disc = jnp.where(any_done, 0.0, gamma ** n_step).astype(jnp.float32)
    # obs stack-validity bits (right-to-left like _stack_window): the
    # anchor frame is always valid; older frames stay valid while no
    # boundary sits between them and the anchor
    vs: list = [None] * stack
    vs[stack - 1] = jnp.ones_like(d2)
    for j in range(stack - 2, -1, -1):
        pb = jnp.roll(b2, stack - 1 - j, axis=1)
        vs[j] = vs[j + 1] & ~pb
    lanes = [a2, rn, disc] + [v.astype(jnp.float32) for v in vs]
    return jnp.stack(lanes, axis=-1).reshape(-1, 3 + stack)


def fused_sample_draw_packed(keys: jax.Array, pack: jax.Array,
                             pm: jax.Array, cdf: jax.Array, mass: jax.Array,
                             n_glob: jax.Array, per_shard: int,
                             slot_cap: int, slot_pad: int, stack: int,
                             n_step: int, betas: jax.Array,
                             num_shards: int):
    """The production draw for the padded-ring path: inverse-CDF draws for
    all ``chain`` steps, metadata from TWO row gathers per sample off the
    ``build_meta_pack`` pack, and the frame-window START rows for the
    Pallas DMA gather (``ops/ring_gather.py``).

    Returns (meta dict [chain, B] incl. ``weight`` and the obs/next-obs
    validity bit-planes ``ovalid``/``nvalid`` [chain, B, stack] u8;
    window-start rows ``ws`` [chain, B] in PADDED shard coords; sampled
    row indices [chain, B] in real coords, OOB-masked for dead shards).
    """
    from jax import lax

    chain = keys.shape[0]
    idx, p = jax.vmap(
        lambda k: draw_from_cdf(k, cdf, pm, mass, per_shard))(keys)
    sub, local = idx // slot_cap, idx % slot_cap
    anchor2 = sub * slot_cap + (local + n_step) % slot_cap
    lanes = pack.shape[-1]
    mp = pack[idx.reshape(-1)].reshape(chain, per_shard, lanes)
    mp2 = pack[anchor2.reshape(-1)].reshape(chain, per_shard, lanes)
    meta = {
        "action": mp[..., 0].astype(jnp.int32),
        "reward": mp[..., 1],
        "discount": mp[..., 2],
        "ovalid": mp[..., 3:3 + stack].astype(jnp.uint8),
        "nvalid": mp2[..., 3:3 + stack].astype(jnp.uint8),
    }
    meta["weight"] = stratified_is_weights(p, mass, n_glob, betas,
                                           num_shards)
    # window start (padded coords): rows [local-stack+1 .. local+n_step]
    # are contiguous there thanks to the ghost rows — always in bounds
    # (slot_pad = slot_cap + window - 1)
    ws = sub * slot_pad + (local - (stack - 1)) % slot_cap
    idx = jnp.where(mass > 0, idx, pm.shape[0])
    return meta, ws.astype(jnp.int32), idx.astype(jnp.int32)


def fused_sample_indices(key: jax.Array, shard_rows: dict[str, jax.Array],
                         cursors: jax.Array, sizes: jax.Array,
                         per_shard: int, slot_cap: int, stack: int,
                         n_step: int, gamma: float, beta: jax.Array,
                         num_shards: int):
    """prep + draw in one call (single-step / test convenience)."""
    pm, cdf, mass, n_glob = fused_sample_prep(
        shard_rows, cursors, sizes, slot_cap, stack, n_step)
    return fused_sample_draw(key, shard_rows, pm, cdf, mass, n_glob,
                             per_shard, slot_cap, stack, n_step, gamma,
                             beta, num_shards)


def fused_sample(key: jax.Array, shard_rows: dict[str, jax.Array],
                 cursors: jax.Array, sizes: jax.Array, per_shard: int,
                 slot_cap: int, stack: int, n_step: int, gamma: float,
                 beta: jax.Array, num_shards: int,
                 ) -> tuple[dict[str, jax.Array], jax.Array]:
    """Single-step convenience: indices + the pixel gather in one call.
    Returns (batch dict incl. ``weight``, with obs as flat ``*_rows``
    stacks — see ``stack_rows_to_obs``; sampled shard-local indices).
    The chained learner path hoists ``fused_sample_prep`` and the gather
    out of its scan instead."""
    batch, oflat, ovalid, nflat, nvalid, idx = fused_sample_indices(
        key, shard_rows, cursors, sizes, per_shard, slot_cap, stack,
        n_step, gamma, beta, num_shards)
    batch = dict(batch)
    batch["obs_rows"] = gather_rows(shard_rows["frames"], oflat, ovalid)
    batch["nobs_rows"] = gather_rows(shard_rows["frames"], nflat, nvalid)
    return batch, idx


def scatter_priorities(prio: jax.Array, maxp: jax.Array, idx: jax.Array,
                       td_abs: jax.Array, alpha: float, eps: float,
                       ) -> tuple[jax.Array, jax.Array]:
    """Same-step priority write-back (one shard): ``p[idx] ← (|TD|+ε)^α``
    and the running pre-α max. No staleness window exists — sampling and
    update happen in one XLA program, so no write can interleave."""
    from jax import lax

    td = jnp.abs(td_abs) + eps
    prio = prio.at[idx].set(td ** alpha)
    maxp = jnp.maximum(maxp, lax.pmax(jnp.max(td), "dp"))
    return prio, maxp


def insert_meta_pack(staged_u8: jax.Array, maxp: jax.Array, *, k: int,
                     row_len: int, rowb: int,
                     alpha: float) -> tuple[jax.Array, jax.Array]:
    """Device-side insert pack for one staged chunk (ISSUE 8 tentpole
    part 3): runs per shard inside the fused write program.

    The host used to pad every staged frame row to the DMA stride
    (``rowb`` bytes, a ``np.zeros`` + slice copy per segment) and view
    the result as packed int32 — per-row host byte churn on the ingest
    hot path. Here the raw staged bytes arrive as-is and the program:

    - pads ``[k, row_len]`` u8 rows to the ``rowb`` DMA stride,
    - packs pixel bytes 4-per-int32 (``bitcast_convert_type`` — on a
      little-endian host this is bit-identical to the reference's
      ``padded.view(np.int32)``, which tests pin),
    - seeds the fresh-row priority from the device running max
      (``maxp ** α``, the scalar every inserted row shares).

    Returns (flat packed rows ``[k · rowb/4]`` int32, priority seed).
    """
    rows = staged_u8.reshape(k, row_len)
    rows = jnp.pad(rows, ((0, 0), (0, rowb - row_len)))
    packed = jax.lax.bitcast_convert_type(
        rows.reshape(k, rowb // 4, 4), jnp.int32)
    return packed.reshape(-1), maxp ** alpha


# ---------------------------------------------------------------------------
# The replay object: DeviceFrameReplay + device metadata/priority twin
# ---------------------------------------------------------------------------


class DevicePERFrameReplay(DeviceFrameReplay):
    """Frame ring + metadata + priorities all device-resident; sampling
    and priority updates happen inside the fused learner step
    (``Learner.train_step_device_per``), so per step the host ships only
    per-slot cursors/sizes (~a few hundred bytes) and reads back nothing.

    Frame-plane layout (round 5 — built for the Pallas row-DMA kernels in
    ``ops/ring_gather.py``; see that module's docstring for the measured
    XLA gather pathology this replaces):

    - frames live in ONE flat int32 array per mesh (pixel bytes packed
      4-per-element — Mosaic's 32-bit index arithmetic caps u8-element
      offsets below the flagship's 8 GB plane), sharded ``P('dp')``; each
      frame row is padded to ``rowb`` bytes (a multiple of the 4 KB 1-D
      tile) so any row range is DMA-alignable.
    - each sub-ring holds ``slot_pad = slot_cap + window - 1`` rows where
      ``window = stack + n_step``: the last ``window - 1`` rows are GHOST
      rows mirroring rows ``0..window-2`` (the flush writes wrap rows
      twice), so every sample's combined obs+next-obs window is ONE
      contiguous ``window``-row DMA — no wrap handling on device.
    - one extra SCRATCH row per shard at the end absorbs the flush's
      padding lanes (the DMA scatter has no out-of-bounds drop).

    Metadata/priority rows stay in REAL (unpadded) coordinates
    ``[capacity]`` — only the pixel plane is padded/ghosted.

    Subclasses ``DeviceFrameReplay`` for all host-side slot bookkeeping
    (stream→slot routing, seal-on-restart, ready gating, the generic
    chunked flush); the overrides pad staged frame rows, widen the
    staging pipeline with metadata columns, and route writes to the
    fused meta-scatter + frame-DMA program.
    """

    def __init__(self, cfg, mesh, frame_shape=(84, 84), stack: int = 4,
                 gamma: float = 0.99, seed: int = 0, write_chunk: int = 64,
                 num_streams: int = 1):
        import dataclasses

        from distributed_deep_q_tpu.compat import shard_map
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributed_deep_q_tpu.ops.ring_gather import scatter_rows
        from distributed_deep_q_tpu.parallel.mesh import AXIS_DP

        self.__cfg_full = cfg  # _alloc_ring (called by super) needs n_step
        # host trees off: priorities live on device
        super().__init__(dataclasses.replace(cfg, prioritized=False), mesh,
                         frame_shape, stack, gamma, seed, write_chunk,
                         num_streams)
        self.prioritized = True
        self._cfg = cfg  # base stored the trees-off copy; β fields match
        self.n_step, self.gamma = cfg.n_step, gamma
        # frame column: the columnar path stages RAW rows — padding to
        # the DMA stride and the 4-per-int32 byte pack happen inside the
        # jit'd insert program (``insert_meta_pack``), so the host-side
        # stage is a pure memcpy of the wire payload. The legacy
        # reference path stages PADDED rows (host zero-fill + .view),
        # which the device pack is pinned bit-identical against.
        self._stage_columns[0] = (
            ((self._row_len,), np.uint8) if self._columnar
            else ((self.rowb,), np.uint8))
        self._stage_columns += [
            ((), np.int32), ((), np.float32), ((), np.uint8), ((), np.uint8)]
        self._di_cache: tuple[np.ndarray, np.ndarray] | None = None
        self._pending_seals: list[tuple[int, int]] = []
        # to_global assembles a contiguous local block per process
        assert self.local_shards == list(range(
            self.local_shards[0], self.local_shards[0]
            + len(self.local_shards))), (
            "mesh device order must group each process's shards "
            "contiguously for P('dp') local-block assembly")

        sharded = NamedSharding(mesh, P(AXIS_DP))
        replicated = NamedSharding(mesh, P())
        cap = self.capacity

        # metadata/priority rings allocated directly on the mesh; the frame
        # ring is ADOPTED from the base allocation (NOT closed over in a
        # jit — a captured multi-GB device array would be lowered constant)
        def init_meta():
            return (jnp.zeros(cap, jnp.int32), jnp.zeros(cap, jnp.float32),
                    jnp.zeros(cap, jnp.uint8), jnp.zeros(cap, jnp.uint8),
                    jnp.zeros(cap, jnp.float32), jnp.ones((), jnp.float32))

        action, reward, done, boundary, prio, maxp = jax.jit(
            init_meta, out_shardings=(sharded, sharded, sharded, sharded,
                                      sharded, replicated))()
        self.dstate = DeviceReplayState(
            frames=self.ring, action=action, reward=reward, done=done,
            boundary=boundary, prio=prio, maxp=maxp)
        self.ring = None  # the frames now live in dstate (single owner)

        # boundary-only scatter for reset_stream: the device boundary ring
        # must mirror the host seal or the fused sampler would compose
        # windows across a dead writer's seam (frames can't be re-written
        # here — they aren't stored host-side — so this touches ONE column)
        def seal(boundary, idx):
            return boundary.at[idx].set(1, mode="drop")

        self._seal_writer = jax.jit(
            shard_map(seal, mesh=mesh,
                      in_specs=(P(AXIS_DP), P(AXIS_DP)),
                      out_specs=P(AXIS_DP), check_vma=False),
            donate_argnums=0)

        alpha = float(cfg.priority_alpha)
        k = self.write_chunk
        rowb, interpret = self.rowb, self._interpret
        row_len, columnar = self._row_len, self._columnar

        def write(rows, midx, act, rew, dn, bnd, sidx, didx, staged):
            if columnar:
                # device-side meta pack (ISSUE 8 tentpole part 3): raw
                # staged bytes → padded/packed DMA rows + priority seed
                staged, new_p = insert_meta_pack(
                    staged, rows.maxp, k=k, row_len=row_len, rowb=rowb,
                    alpha=alpha)
            else:
                new_p = rows.maxp ** alpha
            frames = scatter_rows(sidx, didx, staged, rows.frames,
                                  n=2 * k, rowb=rowb, interpret=interpret)
            return DeviceReplayState(
                frames=frames,
                action=rows.action.at[midx].set(act, mode="drop"),
                reward=rows.reward.at[midx].set(rew, mode="drop"),
                done=rows.done.at[midx].set(dn, mode="drop"),
                boundary=rows.boundary.at[midx].set(bnd, mode="drop"),
                prio=rows.prio.at[midx].set(new_p, mode="drop"),
                maxp=rows.maxp,
            )

        P_ = P
        state_spec = DeviceReplayState(
            frames=P_(AXIS_DP), action=P_(AXIS_DP), reward=P_(AXIS_DP),
            done=P_(AXIS_DP), boundary=P_(AXIS_DP), prio=P_(AXIS_DP),
            maxp=P_())
        # entry/exit layouts pinned to the live arrays' formats: XLA's
        # auto layout assignment may otherwise pick a transposed entry
        # layout for a metadata plane and relayout-copy it every flush
        from distributed_deep_q_tpu.compat import array_format
        state_fmt = jax.tree.map(array_format, self.dstate)
        self._write_full = jax.jit(
            shard_map(write, mesh=mesh,
                      in_specs=(state_spec,) + (P_(AXIS_DP),) * 8,
                      out_specs=state_spec,
                      check_vma=False),
            in_shardings=(state_fmt,) + (None,) * 8,
            out_shardings=state_fmt,
            donate_argnums=0)

    # -- padded frame plane --------------------------------------------------

    def _alloc_ring(self) -> None:
        """Flat padded u8 ring (see class docstring) instead of the base's
        ``[capacity, H·W]`` scatter ring. Runs inside ``super().__init__``;
        geometry derives from attributes the base set before the call."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributed_deep_q_tpu.ops.ring_gather import padded_row_bytes
        from distributed_deep_q_tpu.parallel.mesh import AXIS_DP

        cfg = self.__cfg_full
        self.window = self.stack + int(cfg.n_step)
        assert self.slot_cap >= self.window, (
            f"slot capacity {self.slot_cap} must hold one sample window "
            f"(stack {self.stack} + n_step {cfg.n_step})")
        self.slot_pad = self.slot_cap + self.window - 1
        self.rowb = padded_row_bytes(self._row_len)   # bytes per frame row
        self.rowp = self.rowb // 4                    # int32 per frame row
        self.cap_local_pad = self.subs_per_shard * self.slot_pad
        self.shard_rows = self.cap_local_pad + 1  # +1 scratch row
        # Mosaic scalar index arithmetic is 32-bit: per-shard ELEMENT
        # offsets must stay below 2^31 (ops/ring_gather.py docstring) —
        # 1M frames of 84x84 sit at 2.048e9, inside by 4.6%
        assert self.shard_rows * self.rowp < 2**31, (
            f"per-shard frame plane ({self.shard_rows} rows x {self.rowp} "
            "int32) exceeds Mosaic's 32-bit index range — shard over more "
            "devices/processes or shrink capacity")
        self._interpret = self.mesh.devices.flat[0].platform == "cpu"
        shape = (self.num_shards * self.shard_rows * self.rowp,)
        self.ring = jax.jit(
            lambda: jnp.zeros(shape, jnp.int32),
            out_shardings=NamedSharding(self.mesh, P(AXIS_DP)))()
        self._write = None  # frames flush through _write_full's DMA plane

    # -- overridden write plumbing ------------------------------------------

    def _stage(self, slot: int, local, frames_arr) -> None:
        """Stage (rows, frames, action, reward, done, boundary) — the
        metadata comes from the host slot arrays the rows were just
        written to, gathered vectorized (fancy indexing copies).
        Columnar staging takes the frame rows RAW (pad/pack moved into
        the device insert program); the legacy reference pads here."""
        m = self.slots[slot]
        shard, base_off = self._slot_base(slot)
        k = len(local)
        if self._columnar:
            frames_col = frames_arr
        else:
            frames_col = np.zeros((k, self.rowb), np.uint8)
            frames_col[:, :self._row_len] = frames_arr
        self._stage_rows(shard, (base_off + local).astype(np.int32), (
            frames_col, m.action[local], m.reward[local],
            m.done[local].astype(np.uint8),
            m.boundary[local].astype(np.uint8)))
        self._di_cache = None  # cursors/sizes moved

    def _apply_write(self, idx, cols) -> None:
        """Route each padded chunk ([local_shards, k] planes) to the fused
        write: metadata scatters (real coords, fresh-row priorities seeded
        from the device max) + the frame-row DMA plane (padded coords,
        ghost duplicates, padding lanes → the scratch row). Multi-host:
        every plane assembles this process's local rows into the global
        P('dp') arrays; every process enters this program in lockstep
        (``flush``'s agreed round count)."""
        k = self.write_chunk
        i2 = idx  # [dl, k], in-shard real coords
        ok = i2 < self.cap_local
        sub = np.where(ok, i2 // self.slot_cap, 0)
        local = np.where(ok, i2 % self.slot_cap, 0)
        scratch = self.cap_local_pad
        main = np.where(ok, sub * self.slot_pad + local, scratch)
        ghost = np.where(ok & (local < self.window - 1),
                         sub * self.slot_pad + self.slot_cap + local,
                         scratch)
        dl = i2.shape[0]
        src = np.tile(np.arange(k, dtype=np.int32), (dl, 1))
        sidx = np.concatenate([src, src], axis=1)
        didx = np.concatenate([main, ghost], axis=1).astype(np.int32)
        if self._columnar:
            # raw u8 rows; insert_meta_pack pads + packs them on device
            staged = np.ascontiguousarray(cols[0]).reshape(dl, -1)
        else:
            staged = np.ascontiguousarray(cols[0]).reshape(dl, -1).view(
                np.int32)
        self.dstate = self._write_full(
            self.dstate,
            self.to_global(idx.reshape(-1)),
            *(self.to_global(c.reshape((dl * k,) + t))
              for c, (t, _) in zip(cols[1:], self._stage_columns[1:])),
            self.to_global(sidx.reshape(-1)),
            self.to_global(didx.reshape(-1)),
            self.to_global(staged.reshape(-1)))

    # -- multi-host plumbing -------------------------------------------------

    def to_global(self, local: np.ndarray):
        """Assemble a per-process local plane (this process's contiguous
        block of a ``P('dp')``-sharded array, dim 0) into the global jax
        array; identity on a single process."""
        if self._pc == 1:
            return local
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributed_deep_q_tpu.parallel.mesh import AXIS_DP

        spec = P(*((AXIS_DP,) + (None,) * (local.ndim - 1)))
        factor = self.num_shards // len(self.local_shards)
        gshape = (local.shape[0] * factor,) + local.shape[1:]
        return jax.make_array_from_process_local_data(
            NamedSharding(self.mesh, spec), np.ascontiguousarray(local),
            global_shape=gshape)

    def to_replicated(self, arr: np.ndarray):
        """Replicate a host value onto the (possibly multi-host) mesh."""
        if self._pc == 1:
            return arr
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.make_array_from_process_local_data(
            NamedSharding(self.mesh, P()), np.ascontiguousarray(arr),
            global_shape=arr.shape)

    def sample(self, batch_size: int):
        raise TypeError(
            "DevicePERFrameReplay has no host sample path — sampling is "
            "fused into the learner step (Solver.train_step_device_per)")

    def update_priorities(self, idx, td_abs, sampled_at=None):
        raise TypeError(
            "DevicePERFrameReplay has no host priority write-back — the "
            "fused step scatters (|TD|+eps)^alpha on device itself")

    def reset_stream(self, stream: int) -> None:
        """Seal the stream's current slot on HOST AND DEVICE: the fused
        sampler reads the device boundary ring, so a host-only seal would
        let sampled windows straddle the dead writer's seam.

        Multi-host the device seal DEFERS to the next lockstep flush (the
        seal program runs on global arrays — a per-process immediate
        dispatch would deadlock the collective); the sealed row's
        position is fixed at request time, so later ingest into the same
        slot (which appends past it) cannot invalidate it within a
        chunk."""
        if not (0 <= stream < self.num_streams):
            return
        if self._pc == 1:
            # flush FIRST: rows still staged carry their pre-seal boundary
            # values and a later flush would scatter them over the seal
            self.flush()
        cycle = self._slot_cycle[stream]
        slot = cycle[self._stream_pos[stream] % len(cycle)]
        m = self.slots[slot]
        super().reset_stream(stream)
        if len(m) == 0:
            return
        local = (m._cursor - 1) % self.slot_cap
        shard, base_off = self._slot_base(slot)
        if self._pc > 1:
            self._pending_seals.append((shard, base_off + local))
            return
        # one lane per shard; non-owners carry an OOB index the scatter drops
        idx = np.full(self.num_shards, self.cap_local, np.int32)
        idx[shard] = base_off + local
        self.dstate = self.dstate.replace(
            boundary=self._seal_writer(self.dstate.boundary, idx))

    def flush(self) -> None:
        """Base flush (agreed round count multi-host) + deferred device
        seals (one lockstep seal program per agreed seal round). Seals
        drain AFTER the staged rows so pre-seal rows cannot scatter over
        the seal — the single-process ordering, preserved."""
        super().flush()
        if self._pc == 1:
            return
        from distributed_deep_q_tpu.parallel.multihost import global_max_int

        per_shard: dict[int, list[int]] = {}
        for shard, row in self._pending_seals:
            per_shard.setdefault(shard, []).append(row)
        self._pending_seals.clear()
        rounds = global_max_int(max((len(v) for v in per_shard.values()),
                                    default=0))
        dl = len(self.local_shards)
        for r in range(rounds):
            idx = np.full(dl, self.cap_local, np.int32)
            for li, s in enumerate(self.local_shards):
                rows = per_shard.get(s, [])
                if r < len(rows):
                    idx[li] = rows[r]
            self.dstate = self.dstate.replace(
                boundary=self._seal_writer(self.dstate.boundary,
                                           self.to_global(idx)))

    # -- learner-side inputs -------------------------------------------------
    # (β comes from the inherited ``beta`` property; the fused path never
    # calls host ``sample``, so the anneal advances via next_betas)

    def next_betas(self, k: int) -> np.ndarray:
        """β values for the next ``k`` fused steps, advancing the anneal
        BEFORE each read — same ordering as the host path, whose
        ``sample()`` increments ``_samples`` before computing weights."""
        out = np.empty(k, np.float32)
        for i in range(k):
            self._samples += 1
            out[i] = self.beta
        return out

    def device_inputs(self):
        """(cursors, sizes) int32 host arrays for this process's LOCAL
        shards, shard-major ``[dl·subs]`` — the local block of the global
        ``P('dp')`` plane (``to_global`` assembles it; single-process the
        local block IS the plane).

        Cached between writes: the idle hot loop (no ingest since the last
        step) pays one ``is None`` check instead of a Python pass over all
        slots — at the apex preset's 256 streams that pass is real per-step
        host time (VERDICT r3 weak #3)."""
        if self._di_cache is None:
            d, subs = self.num_shards, self.subs_per_shard
            dl = len(self.local_shards)
            cursors = np.zeros(dl * subs, np.int32)
            sizes = np.zeros(dl * subs, np.int32)
            for li, s in enumerate(self.local_shards):
                for sub in range(subs):
                    m = self.slots[sub * d + s]
                    cursors[li * subs + sub] = m._cursor
                    sizes[li * subs + sub] = len(m)
            self._di_cache = (cursors, sizes)
        return self._di_cache
