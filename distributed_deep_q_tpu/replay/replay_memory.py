"""Replay memory — host-RAM transition ring buffers (SURVEY.md §1 L3 [M]).

The reference ``ReplayMemory`` is a ring buffer of (s, a, r, s', done) with a
uniform ``.sample(batch_size)`` minibatch API and 4-frame stacking [M][P]
(HDF5-backed in the repo family [R]). Rebuilt TPU-first as numpy host buffers
(no HDF5 in the hot path) feeding a double-buffered ``device_put`` pipeline
(``replay/staging.py``); an optional C++ core (``native/``) accelerates the
gather/sampling inner loops.

Two storage strategies, same ``add``/``sample``/``__len__`` surface:

- ``ReplayMemory`` — explicit transitions: stores obs and next_obs as given.
  Right for vector envs (CartPole) and for RPC-fed transitions where the
  writer interleaves many actor streams (no temporal adjacency assumed).

- ``FrameStackReplay`` — memory-optimal Atari mode: stores ONE frame per
  step plus (action, reward, done) and composes the 4-frame stack, the
  n-step return, and the next-state stack at sample time from ring
  adjacency (Nature-DQN storage trick). Requires a single temporally-
  contiguous writer stream; the replay server gives each actor its own
  shard to preserve that invariant.

``sample`` returns a dict batch with keys
``obs, action, reward, next_obs, discount, weight, index`` where
``reward`` is the n-step-summed return, ``discount`` = γⁿ·(1-done) ready for
``targets = reward + discount * max_a Q⁻(next_obs)``, ``weight`` the
importance weight (ones for uniform), and ``index`` the slot indices for
PER priority updates (``replay/prioritized.py``).
"""

from __future__ import annotations

from typing import Mapping

import numpy as np


class ReplayMemory:
    """Uniform ring buffer over explicit (s, a, r, s', discount) transitions."""

    prioritized = False  # uniform sampling; PER wraps via replay/prioritized.py

    def ready(self, learn_start: int) -> bool:
        return len(self) >= learn_start

    def __init__(
        self,
        capacity: int,
        obs_shape: tuple[int, ...],
        obs_dtype: np.dtype = np.float32,
        seed: int = 0,
    ):
        self.capacity = int(capacity)
        self.obs = np.zeros((capacity,) + tuple(obs_shape), obs_dtype)
        self.next_obs = np.zeros_like(self.obs)
        self.action = np.zeros(capacity, np.int32)
        self.reward = np.zeros(capacity, np.float32)
        self.discount = np.zeros(capacity, np.float32)
        self._cursor = 0
        self._size = 0
        self._steps_added = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    @property
    def steps_added(self) -> int:
        return self._steps_added

    def add(self, obs, action, reward, next_obs, discount) -> int:
        """Add one transition; returns the slot index it landed in."""
        i = self._cursor
        self.obs[i] = obs
        self.next_obs[i] = next_obs
        self.action[i] = action
        self.reward[i] = reward
        self.discount[i] = discount
        self._cursor = (i + 1) % self.capacity
        self._size = min(self._size + 1, self.capacity)
        self._steps_added += 1
        return i

    def add_batch(self, batch: Mapping[str, np.ndarray]) -> np.ndarray:
        """Vectorized add for RPC-fed transition batches; returns slot indices."""
        n = len(batch["action"])
        idx = (self._cursor + np.arange(n)) % self.capacity
        self.obs[idx] = batch["obs"]
        self.next_obs[idx] = batch["next_obs"]
        self.action[idx] = batch["action"]
        self.reward[idx] = batch["reward"]
        self.discount[idx] = batch["discount"]
        self._cursor = int((self._cursor + n) % self.capacity)
        self._size = min(self._size + n, self.capacity)
        self._steps_added += n
        return idx

    def gather(self, idx: np.ndarray) -> dict[str, np.ndarray]:
        return {
            "obs": self.obs[idx],
            "action": self.action[idx],
            "reward": self.reward[idx],
            "next_obs": self.next_obs[idx],
            "discount": self.discount[idx],
            "weight": np.ones(len(idx), np.float32),
            "index": idx.astype(np.int32),
        }

    def sample(self, batch_size: int) -> dict[str, np.ndarray]:
        assert self._size > 0, "sample() from empty ReplayMemory"
        idx = self._rng.integers(0, self._size, size=batch_size)
        return self.gather(idx)


class FrameStackReplay:
    """Single-frame ring with stack + n-step composition at sample time.

    Stores per step: frame uint8 [H, W], action, reward, done, boundary.
    ``done`` cuts the bootstrap (true termination); ``boundary`` marks any
    episode end including time-limit truncation — frame stacks never cross a
    boundary, and candidate transitions whose n-step window crosses a
    truncation-only boundary (boundary & ~done: no valid next state, but
    bootstrapping is still correct in principle) are excluded from sampling
    rather than corrupting Bellman targets. A sampled transition at slot
    ``i`` is:

      obs      = frames[i-stack+1 .. i]   (zeroed before episode start)
      reward   = Σ_{k<m} γᵏ r_{i+k}       (m = steps until first done, ≤ n)
      discount = γᵐ if no done in window else 0
      next_obs = frames[i+n-stack+1 .. i+n]

    Requires adds to be temporally contiguous (single writer stream).
    """

    prioritized = False

    def ready(self, learn_start: int) -> bool:
        return (len(self) >= max(learn_start, self.stack + self.n_step + 1)
                and self.valid_fraction() > 0)

    def __init__(
        self,
        capacity: int,
        frame_shape: tuple[int, int] = (84, 84),
        stack: int = 4,
        n_step: int = 1,
        gamma: float = 0.99,
        seed: int = 0,
        store_frames: bool = True,
    ):
        """``store_frames=False`` keeps only metadata (action/reward/done/
        boundary + ring indices) — the mode used by the device-resident
        replay (``replay/device_ring.py``), where frames live in HBM and
        this class supplies index/validity/n-step composition via
        ``gather_meta``."""
        self.capacity = int(capacity)
        self.stack = int(stack)
        self.n_step = int(n_step)
        self.gamma = float(gamma)
        self.frames = (np.zeros((capacity,) + tuple(frame_shape), np.uint8)
                       if store_frames else None)
        self.action = np.zeros(capacity, np.int32)
        self.reward = np.zeros(capacity, np.float32)
        self.done = np.zeros(capacity, bool)       # cuts bootstrap
        self.boundary = np.zeros(capacity, bool)   # episode end incl. truncation
        self._cursor = 0
        self._size = 0
        self._steps_added = 0
        self._rng = np.random.default_rng(seed)
        # γ^k lookup for the n-step return
        self._gammas = gamma ** np.arange(n_step + 1, dtype=np.float32)

    def __len__(self) -> int:
        return self._size

    @property
    def steps_added(self) -> int:
        return self._steps_added

    def add(self, frame, action, reward, done, boundary=None) -> int:
        i = self._cursor
        if self.frames is not None:
            self.frames[i] = frame
        self.action[i] = action
        self.reward[i] = reward
        self.done[i] = done
        self.boundary[i] = done if boundary is None else boundary
        self._cursor = (i + 1) % self.capacity
        self._size = min(self._size + 1, self.capacity)
        self._steps_added += 1
        return i

    def add_batch(self, batch: Mapping[str, np.ndarray]) -> np.ndarray:
        n = len(batch["action"])
        idx = (self._cursor + np.arange(n)) % self.capacity
        if self.frames is not None:
            self.frames[idx] = batch["frame"]
        self.action[idx] = batch["action"]
        self.reward[idx] = batch["reward"]
        self.done[idx] = batch["done"]
        self.boundary[idx] = batch.get("boundary", batch["done"])
        self._cursor = int((self._cursor + n) % self.capacity)
        self._size = min(self._size + n, self.capacity)
        self._steps_added += n
        return idx

    def seal_stream(self) -> None:
        """Mark an episode boundary on the last written row.

        Called when the writer stream changes identity mid-episode (actor
        crash → respawn reusing the stream id): without the seal, sampled
        stacks and n-step windows could straddle the dead actor's half
        episode and the replacement's first episode. ``done`` stays False —
        the truncation-only boundary excludes straddling windows from
        sampling rather than faking a terminal.
        """
        if self._size:
            self.boundary[(self._cursor - 1) % self.capacity] = True

    # -- sampling ----------------------------------------------------------

    def _invalid(self, idx: np.ndarray) -> np.ndarray:
        """True where a candidate slot can't form a full transition.

        A slot is invalid when its [i-stack+1, i+n] window crosses the write
        cursor (frames from two different epochs of the ring), falls off
        either end before the ring is full, or its n-step window crosses a
        truncation-only boundary (episode ended by time limit: no valid
        next state stored, so the transition cannot form a correct target).
        """
        if self._size < self.capacity:
            bad = (idx < self.stack - 1) | (idx + self.n_step >= self._size)
        else:
            # distance from the cursor going backwards; the (stack-1 + n)
            # slots straddling the cursor are unusable
            back = (idx - self._cursor) % self.capacity
            bad = (back >= self.capacity - self.n_step) | (back < self.stack - 1)
        steps = (idx[:, None] + np.arange(self.n_step)[None, :]) % self.capacity
        trunc_only = self.boundary[steps] & ~self.done[steps]
        return bad | trunc_only.any(axis=1)

    def valid_fraction(self) -> float:
        if self._size == 0:
            return 0.0
        window = self.stack - 1 + self.n_step
        return max(0.0, 1.0 - window / max(self._size, 1))

    def sample_indices(self, batch_size: int) -> np.ndarray:
        assert self._size > self.stack + self.n_step, "replay too small to sample"
        idx = self._rng.integers(0, self._size, size=batch_size)
        bad = self._invalid(idx)
        tries = 0
        while bad.any():
            idx[bad] = self._rng.integers(0, self._size, size=int(bad.sum()))
            bad = self._invalid(idx)
            tries += 1
            if tries > 1000:  # e.g. every stored episode truncated + tiny ring
                raise RuntimeError(
                    f"FrameStackReplay: no sampleable transition found after "
                    f"{tries} rounds (size={self._size}, stack={self.stack}, "
                    f"n_step={self.n_step}); buffer likely contains only "
                    f"truncated episodes shorter than stack-1+n_step")
        return idx

    def _stack_indices(self, idx: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(frame indices [B, stack] oldest-first, validity mask [B, stack]).

        A frame at offset k is valid iff no episode boundary lies between it
        and the anchor frame; invalid frames are zero-filled (episode-start
        padding), matching ``FrameStacker.reset`` semantics.
        """
        b, cap = len(idx), self.capacity
        offs = np.arange(self.stack - 1, -1, -1)
        oidx = (idx[:, None] - offs[None, :]) % cap          # [B, stack]
        prev_done = self.boundary[(oidx - 1) % cap]          # boundary before frame
        # valid[b, j]: product over frames newer than j of (no boundary
        # before them), computed right-to-left (newest frame always valid).
        valid = np.ones((b, self.stack), bool)
        for j in range(self.stack - 2, -1, -1):
            valid[:, j] = valid[:, j + 1] & ~prev_done[:, j + 1]
        return oidx, valid

    def gather_meta(self, idx: np.ndarray) -> dict[str, np.ndarray]:
        """Everything ``gather`` needs except the frame pixels themselves:
        stack indices + validity masks for s and s', the n-step return, and
        the bootstrap discount. This is the host side of the device-resident
        replay split — frames are gathered in HBM from these indices."""
        b, cap, n = len(idx), self.capacity, self.n_step

        oidx, valid = self._stack_indices(idx)

        steps = (idx[:, None] + np.arange(n)[None, :]) % cap  # [B, n]
        d = self.done[steps]                                   # [B, n]
        # continuing[b, k] = no done strictly before step k in the window
        continuing = np.ones((b, n), bool)
        if n > 1:
            continuing[:, 1:] = ~np.cumsum(d[:, :-1], axis=1).astype(bool)
        rewards = self.reward[steps] * continuing
        reward = (rewards * self._gammas[:n][None, :]).sum(axis=1)
        any_done = (d & continuing).any(axis=1)
        discount = np.where(any_done, 0.0, self._gammas[n]).astype(np.float32)

        noidx, nvalid = self._stack_indices((idx + n) % cap)
        return {
            "oidx": oidx.astype(np.int32),
            "valid": valid,
            "noidx": noidx.astype(np.int32),
            "nvalid": nvalid,
            "action": self.action[idx],
            "reward": reward.astype(np.float32),
            "discount": discount,
            "weight": np.ones(b, np.float32),
            "index": idx.astype(np.int32),
        }

    def gather(self, idx: np.ndarray) -> dict[str, np.ndarray]:
        assert self.frames is not None, \
            "gather() needs stored frames; metadata-only rings use gather_meta()"
        m = self.gather_meta(idx)
        obs = self.frames[m.pop("oidx")] \
            * m.pop("valid")[..., None, None].astype(np.uint8)
        next_obs = self.frames[m.pop("noidx")] \
            * m.pop("nvalid")[..., None, None].astype(np.uint8)
        # frames-last layout for the CNN: [B, H, W, stack]
        m["obs"] = np.moveaxis(obs, 1, -1)
        m["next_obs"] = np.moveaxis(next_obs, 1, -1)
        return m

    def sample(self, batch_size: int) -> dict[str, np.ndarray]:
        return self.gather(self.sample_indices(batch_size))
