"""Device-resident replay — frames live in HBM, metadata on host.

The TPU-first redesign of the replay data path (SURVEY.md §7.3 item 1: "this
is where the 50× target is won or lost"). The reference streams full pixel
minibatches host→device every step (Caffe blob loads, SURVEY §3.1); a
pmap-fed rebuild doing the same ships ~29 MB/step at batch 512 — measured
at ~160 ms over this container's TPU link vs a 0.2 ms train step. Instead:

- **Frames enter HBM once, at actor rate.** A uint8 ring ``[capacity, H, W]``
  lives on the learner mesh, sharded over the ``dp`` axis (each device owns
  a contiguous shard — Ape-X-style per-learner replay shards). Actor streams
  append in fixed-size chunks through a donated ``shard_map`` scatter.
- **The train step gathers on device.** The host samples *indices* (uniform
  or PER sum-tree — pointer-chasing stays on host, SURVEY §7.3 item 2),
  composes n-step returns/validity masks from metadata, and ships only
  ``[B, stack]`` int32 indices + a few ``[B]`` scalars (~50 KB). Frame-stack
  composition (gather + zero-masking + transpose) happens inside the jitted
  step, reading HBM at memory bandwidth.

Sharding invariants:
- Each episode is routed whole to one shard (``add`` advances the shard
  pointer on episode boundaries; RPC streams pin ``stream → shard``), so
  temporal adjacency — which frame-stacking relies on — holds per shard.
- Sampling draws ``batch/D`` from every shard and concatenates in mesh
  order, matching ``PartitionSpec('dp')`` row-block layout, so each device
  gathers only from its local shard — no cross-device collective in the
  data path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_deep_q_tpu.config import ReplayConfig
from distributed_deep_q_tpu.parallel.mesh import AXIS_DP
from distributed_deep_q_tpu.replay.prioritized import PrioritizedReplay
from distributed_deep_q_tpu.replay.replay_memory import FrameStackReplay


def compose_stacks(ring: jax.Array, oidx: jax.Array,
                   valid: jax.Array) -> jax.Array:
    """[capL, H, W] ring + [B, stack] indices/mask → [B, H, W, stack] uint8.

    Pure jax; runs per-device inside the learner's shard_map (indices are
    shard-local). Invalid frames (preceding episode start) zero out, matching
    ``FrameStackReplay.gather`` / ``FrameStacker.reset`` semantics.
    """
    frames = ring[oidx]                                   # [B, S, H, W]
    frames = frames * valid[..., None, None].astype(jnp.uint8)
    return jnp.moveaxis(frames, 1, -1)                    # [B, H, W, S]


class DeviceFrameReplay:
    """HBM frame ring + host metadata/priorities, one logical buffer.

    Reference-parity surface (``add`` / ``sample`` / ``__len__`` [M]) plus
    ``update_priorities``; ``sample`` returns an *index batch* whose pixels
    are composed on device by the learner's ring train step.
    """

    def __init__(
        self,
        cfg: ReplayConfig,
        mesh: Mesh,
        frame_shape: tuple[int, int] = (84, 84),
        stack: int = 4,
        gamma: float = 0.99,
        seed: int = 0,
        write_chunk: int = 64,
    ):
        self.mesh = mesh
        self.num_shards = mesh.shape[AXIS_DP]
        d = self.num_shards
        self.cap_local = int(cfg.capacity) // d
        assert self.cap_local > 0 and cfg.batch_size % d == 0, \
            f"capacity {cfg.capacity} / batch {cfg.batch_size} must split over {d} shards"
        self.capacity = self.cap_local * d
        self.stack = int(stack)
        self.frame_shape = tuple(frame_shape)
        self.write_chunk = int(write_chunk)
        self.prioritized = bool(cfg.prioritized)

        def meta_ring(i: int) -> FrameStackReplay:
            return FrameStackReplay(
                self.cap_local, frame_shape, stack, cfg.n_step, gamma,
                seed=seed + i, store_frames=False)

        if self.prioritized:
            self.shards = [
                PrioritizedReplay(
                    meta_ring(i), alpha=cfg.priority_alpha,
                    beta0=cfg.priority_beta0,
                    beta_steps=cfg.priority_beta_steps,
                    eps=cfg.priority_eps, seed=seed + 1000 + i)
                for i in range(d)]
        else:
            self.shards = [meta_ring(i) for i in range(d)]

        # HBM ring, allocated directly with its dp sharding (no host copy).
        ring_sharding = NamedSharding(mesh, P(AXIS_DP))
        shape = (self.capacity,) + self.frame_shape
        self.ring = jax.jit(
            lambda: jnp.zeros(shape, jnp.uint8),
            out_shardings=ring_sharding)()

        # Donated scatter-writer: each device writes its chunk into its own
        # ring shard; padding lanes carry idx == cap_local and are dropped.
        def write(ring_local, idx, frames):
            return ring_local.at[idx].set(frames, mode="drop")

        self._write = jax.jit(
            shard_map(write, mesh=mesh,
                      in_specs=(P(AXIS_DP), P(AXIS_DP), P(AXIS_DP)),
                      out_specs=P(AXIS_DP)),
            donate_argnums=0)

        # host-side staging: per-shard pending (local_idx, frame)
        self._pending: list[list[tuple[int, np.ndarray]]] = [[] for _ in range(d)]
        self._shard = 0  # episode-routing pointer for single-stream add()

    # -- bookkeeping --------------------------------------------------------

    def _meta(self, s: int) -> FrameStackReplay:
        sh = self.shards[s]
        return sh.base if isinstance(sh, PrioritizedReplay) else sh

    def __len__(self) -> int:
        return sum(len(self._meta(s)) for s in range(self.num_shards))

    def ready(self, learn_start: int) -> bool:
        """True when sampling can proceed: aggregate fill reached AND every
        shard can form transitions (sample draws batch/D from *each* shard,
        and episodes route whole to shards, so early on some shards may
        still be empty — SURVEY §7.3 item 6)."""
        if len(self) < learn_start:
            return False
        return all(
            len(m) > m.stack + m.n_step and m.valid_fraction() > 0
            for m in (self._meta(s) for s in range(self.num_shards)))

    @property
    def steps_added(self) -> int:
        return sum(self._meta(s).steps_added for s in range(self.num_shards))

    # -- write path ---------------------------------------------------------

    def add(self, frame, action, reward, done, boundary=None) -> int:
        """Single-stream add; episodes route whole to one shard and the
        shard pointer advances at each episode boundary."""
        s = self._shard
        i = self.shards[s].add(None, action, reward, done, boundary=boundary)
        self._pending[s].append((i, np.asarray(frame, np.uint8)))
        episode_over = done if boundary is None else boundary
        if episode_over:
            self._shard = (s + 1) % self.num_shards
        if len(self._pending[s]) >= self.write_chunk:
            self.flush()
        return s * self.cap_local + i

    def add_batch(self, batch, stream: int = 0) -> np.ndarray:
        """RPC-fed contiguous chunk from one actor stream (→ one shard)."""
        s = stream % self.num_shards
        idx = self.shards[s].add_batch(
            {k: v for k, v in batch.items() if k != "frame"} | {
                "action": batch["action"], "reward": batch["reward"],
                "done": batch["done"],
                "boundary": batch.get("boundary", batch["done"])})
        for i, f in zip(idx, batch["frame"]):
            self._pending[s].append((int(i), np.asarray(f, np.uint8)))
        if max(len(p) for p in self._pending) >= self.write_chunk:
            self.flush()
        return idx + s * self.cap_local

    def flush(self) -> None:
        """Push all staged frames to HBM in fixed-shape chunks.

        Every flush writes ``write_chunk`` lanes per shard (one compiled
        program); shards with fewer pending frames pad with out-of-bounds
        indices that the scatter drops.
        """
        while any(self._pending):
            k, d = self.write_chunk, self.num_shards
            idx = np.full((d, k), self.cap_local, np.int32)  # OOB = dropped
            frames = np.zeros((d, k) + self.frame_shape, np.uint8)
            for s in range(d):
                take, self._pending[s] = (self._pending[s][:k],
                                          self._pending[s][k:])
                for j, (i, f) in enumerate(take):
                    idx[s, j], frames[s, j] = i, f
            self.ring = self._write(
                self.ring, idx.reshape(d * k),
                frames.reshape((d * k,) + self.frame_shape))

    # -- sample path --------------------------------------------------------

    def sample(self, batch_size: int) -> dict[str, np.ndarray]:
        """Index batch (no pixels): per-shard draws concatenated in mesh
        order so ``P('dp')`` row-blocks land on the owning devices."""
        self.flush()
        d = self.num_shards
        per = batch_size // d
        parts, weights, sampled_at = [], [], []
        for s in range(d):
            sh = self.shards[s]
            if self.prioritized:
                idx, w = sh.sample_indices_weighted(per)
            else:
                idx, w = sh.sample_indices(per), np.ones(per)
            m = self._meta(s).gather_meta(idx)
            m["index"] = (idx + s * self.cap_local).astype(np.int32)
            parts.append(m)
            weights.append(w)
            sampled_at.append(self._meta(s).steps_added)
        batch = {k: np.concatenate([p[k] for p in parts])
                 for k in parts[0]}
        w = np.concatenate(weights)
        batch["weight"] = (w / w.max()).astype(np.float32)
        batch["valid"] = batch["valid"].astype(np.uint8)
        batch["nvalid"] = batch["nvalid"].astype(np.uint8)
        batch["_sampled_at"] = tuple(sampled_at)
        return batch

    def update_priorities(self, idx: np.ndarray, td_abs: np.ndarray,
                          sampled_at=None) -> None:
        if not self.prioritized:
            return
        idx = np.asarray(idx, np.int64)
        shard_of = idx // self.cap_local
        for s in range(self.num_shards):
            pick = shard_of == s
            if not pick.any():
                continue
            self.shards[s].update_priorities(
                idx[pick] % self.cap_local, np.asarray(td_abs)[pick],
                sampled_at=None if sampled_at is None else sampled_at[s])
