"""Device-resident replay — frames live in HBM, metadata on host.

The TPU-first redesign of the replay data path (SURVEY.md §7.3 item 1: "this
is where the 50× target is won or lost"). The reference streams full pixel
minibatches host→device every step (Caffe blob loads, SURVEY §3.1); a
pmap-fed rebuild doing the same ships ~29 MB/step at batch 512 — measured
at ~160 ms over this container's TPU link vs a 0.2 ms train step. Instead:

- **Frames enter HBM once, at actor rate.** A uint8 ring ``[capacity, H·W]``
  (frames flattened row-wise — TPU tiling-aware layout, see
  ``compose_stacks``) lives on the learner mesh, sharded over the ``dp``
  axis (each device owns a contiguous shard — Ape-X-style per-learner
  replay shards). Writers append in fixed-size chunks through a donated
  ``shard_map`` scatter.
- **The train step gathers on device.** The host samples *indices* (uniform
  or PER sum-tree — pointer-chasing stays on host, SURVEY §7.3 item 2),
  composes n-step returns/validity masks from metadata, and ships only
  ``[B, stack]`` int32 indices + a few ``[B]`` scalars (~50 KB). Frame-stack
  composition (gather + zero-masking + transpose) happens inside the jitted
  step, reading HBM at memory bandwidth.

Layout — shards and stream slots:

    device shard s owns ring rows [s·cap_local, (s+1)·cap_local)
    each shard is split into ``subs_per_shard`` SLOTS of ``slot_cap`` rows
    slot g (global id) lives on shard g % D at sub-ring g // D

Frame stacking relies on temporal adjacency, so every slot has exactly ONE
writer stream at a time. Stream i owns slots {g : g % num_streams == i} and
cycles through them at episode boundaries; with fewer streams than shards a
single stream still reaches every shard (episode round-robin), and with more
streams than shards each shard hosts several sub-rings instead of
interleaving writers. Sampling draws ``batch/D`` rows per shard (allocated
across its slots by sampleable/priority mass) and concatenates in mesh
order, matching ``PartitionSpec('dp')`` row-block layout — each device
gathers only from its local shard, no cross-device collective in the data
path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from distributed_deep_q_tpu.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_deep_q_tpu import tracing
from distributed_deep_q_tpu.config import ReplayConfig
from distributed_deep_q_tpu.parallel.mesh import AXIS_DP
from distributed_deep_q_tpu.replay.prioritized import (
    SumTree, allocate_proportional, beta_at, filter_stale,
    sample_valid_from_tree)
from distributed_deep_q_tpu.replay.replay_memory import FrameStackReplay


def compose_stacks(ring: jax.Array, oidx: jax.Array, valid: jax.Array,
                   frame_shape: tuple[int, int] = (84, 84)) -> jax.Array:
    """[capL, H·W] ring + [B, stack] indices/mask → [B, H, W, stack] uint8.

    Pure jax; runs per-device inside the learner's shard_map (indices are
    shard-local). Invalid frames (preceding episode start) zero out, matching
    ``FrameStackReplay.gather`` / ``FrameStacker.reset`` semantics.

    The ring stores frames FLATTENED to one [H·W] row per frame: TPU tiles
    the two minor dims of an array ((32, 128) lanes for 8-bit types), so a
    [cap, 84, 84] ring pads each frame to 96×128 — 1.74× HBM waste that
    OOMs a 16 GB chip at the config-2 1M-frame capacity. Flattened, the
    pad is 7056→7168 (1.6%) and the full 1M ring fits a single v5e with
    room for the step. The gather is row-wise either way; only the final
    reshape (free, layout-compatible) differs.
    """
    frames = ring[oidx]                                   # [B, S, H·W]
    frames = frames * valid[..., None].astype(jnp.uint8)
    frames = frames.reshape(frames.shape[:2] + tuple(frame_shape))
    return jnp.moveaxis(frames, 1, -1)                    # [B, H, W, S]


class DeviceFrameReplay:
    """HBM frame ring + host metadata/priorities, one logical buffer.

    Reference-parity surface (``add`` / ``sample`` / ``__len__`` [M]) plus
    ``update_priorities``; ``sample`` returns an *index batch* whose pixels
    are composed on device by the learner's ring train step.
    """

    prioritized: bool

    def __init__(
        self,
        cfg: ReplayConfig,
        mesh: Mesh,
        frame_shape: tuple[int, int] = (84, 84),
        stack: int = 4,
        gamma: float = 0.99,
        seed: int = 0,
        write_chunk: int = 64,
        num_streams: int = 1,
    ):
        self.mesh = mesh
        d = self.num_shards = mesh.shape[AXIS_DP]
        self.num_streams = max(int(num_streams), 1)
        # multi-controller topology (SURVEY §7.3 item 6): this process
        # owns only the shards whose devices it hosts; its streams route
        # to slots on those shards, its staging covers only them, and
        # flush planes assemble per-process local rows into the global
        # sharded arrays. Geometry (subs/slot_cap) must be identical on
        # every process, so it derives from the GLOBAL stream count.
        self._pc = jax.process_count()
        self._pid = jax.process_index()
        self.local_shards = [s for s, dev in enumerate(mesh.devices.flat)
                             if dev.process_index == self._pid]
        total_streams = self.num_streams * self._pc
        self.subs_per_shard = -(-max(total_streams, d) // d)  # ceil
        g = self.num_slots = self.subs_per_shard * d
        self.slot_cap = int(cfg.capacity) // g
        assert self.slot_cap > 0 and cfg.batch_size % d == 0, (
            f"capacity {cfg.capacity} must split over {g} stream slots and "
            f"batch {cfg.batch_size} over {d} shards")
        # one flush chunk must never wrap a sub-ring: a wrap would scatter
        # duplicate in-shard offsets in one .at[].set (unspecified winner →
        # stale pixels under fresh metadata), so clamp the chunk size
        write_chunk = min(int(write_chunk), self.slot_cap)
        self.cap_local = self.slot_cap * self.subs_per_shard
        self.capacity = self.cap_local * d
        self.stack = int(stack)
        self.frame_shape = tuple(frame_shape)
        self.write_chunk = int(write_chunk)
        self.prioritized = bool(cfg.prioritized)
        self._cfg = cfg
        self._rng = np.random.default_rng(seed)

        # per-slot metadata rings (single writer each → adjacency holds)
        self.slots = [
            FrameStackReplay(self.slot_cap, frame_shape, stack, cfg.n_step,
                             gamma, seed=seed + i, store_frames=False)
            for i in range(g)]
        # per-slot priority trees with SHARED max-priority/β bookkeeping
        self.trees = ([SumTree(self.slot_cap, use_native=cfg.use_native)
                       for _ in range(g)]
                      if self.prioritized else None)
        self.max_priority = 1.0
        self._samples = 0

        # stream → its slot cycle over this process's LOCAL slots (stream
        # i owns every num_streams-th local slot; single-process this is
        # exactly the old {g : g % num_streams == i} assignment since
        # local slots are all slots in order)
        local_set = set(self.local_shards)
        local_slots = [s for s in range(g) if s % d in local_set]
        self._slot_cycle = [
            [s for j, s in enumerate(local_slots) if j % self.num_streams == i]
            for i in range(self.num_streams)]
        self._stream_pos = [0] * self.num_streams
        # multi-host: flushes must be LOCKSTEP collectives (the scatter
        # runs on global arrays), so ingest defers them to the chunk
        # boundary where every process flushes an agreed round count
        self.defer_flush = self._pc > 1

        self._row_len = int(np.prod(self.frame_shape))
        self._alloc_ring()

        # host staging: _stage_columns describes the staged columns'
        # (tail shape, dtype); subclasses (device_per) extend it with
        # metadata columns. Two interchangeable backends (ISSUE 8):
        # - columnar (default): per-shard preallocated column buffers,
        #   one memcpy per column per staged segment (replay/columnar.py)
        # - legacy: per-shard FIFO of (in-shard offsets [n], *columns)
        #   array tuples — the bit-identical reference the columnar
        #   path is pinned against (tests/test_columnar_ingest.py)
        self._stage_columns: list[tuple[tuple[int, ...], type]] = [
            ((self._row_len,), np.uint8)]
        self._columnar = bool(getattr(cfg, "staging_columnar", True))
        self._staging_depth = int(getattr(cfg, "staging_depth", 4096))
        self._stages: list | None = None  # built lazily: subclasses widen
        self._pending: list[list[tuple]] = [[] for _ in range(d)]
        self._pending_rows = [0] * d
        # pre-assembled flush planes (ISSUE 10 shard-aware drain): FIFO
        # of (idx, cols, rows) built host-side by prepare_rounds; the
        # next flush() dispatches these BEFORE assembling fresh rounds,
        # so write order is staged order regardless of who assembled
        self._prepared: list[tuple[np.ndarray, list, int]] = []
        self._prepared_rows = 0
        self._drain = None  # optional IngestDrain (start_drain)
        self._drain_enabled = bool(getattr(cfg, "ingest_drain", True))
        self._drain_min = int(getattr(cfg, "drain_min_rows", 0))

    def _alloc_ring(self) -> None:
        """Allocate the HBM frame plane + its scatter-writer. Overridden by
        ``DevicePERFrameReplay`` (flat padded ring + Pallas row-DMA).

        Frames are flattened to [H·W] rows — see compose_stacks for why
        (TPU (32,128) tiling of the minor dims). Allocated directly with
        the dp sharding (no host copy); the donated scatter lets each
        device write its chunk into its own ring shard, padding lanes
        carry idx == cap_local and are dropped."""
        ring_sharding = NamedSharding(self.mesh, P(AXIS_DP))
        shape = (self.capacity, self._row_len)
        self.ring = jax.jit(
            lambda: jnp.zeros(shape, jnp.uint8),
            out_shardings=ring_sharding)()

        def write(ring_local, idx, frames):
            return ring_local.at[idx].set(frames, mode="drop")

        self._write = jax.jit(
            shard_map(write, mesh=self.mesh,
                      in_specs=(P(AXIS_DP), P(AXIS_DP), P(AXIS_DP)),
                      out_specs=P(AXIS_DP)),
            donate_argnums=0)

    # -- layout helpers -----------------------------------------------------

    def _slot_base(self, slot: int) -> tuple[int, int]:
        """(shard, in-shard base offset) of a slot's sub-ring."""
        return slot % self.num_shards, (slot // self.num_shards) * self.slot_cap

    def _global_index(self, slot: int, local: np.ndarray) -> np.ndarray:
        shard, base = self._slot_base(slot)
        return shard * self.cap_local + base + local

    def _slot_of_global(self, gidx: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """global ring row → (slot id, slot-local index)."""
        shard, rem = gidx // self.cap_local, gidx % self.cap_local
        sub, local = rem // self.slot_cap, rem % self.slot_cap
        return sub * self.num_shards + shard, local

    # -- bookkeeping --------------------------------------------------------

    def __len__(self) -> int:
        return sum(len(m) for m in self.slots)

    def pending_rows(self) -> int:
        """Rows staged or pre-assembled but not yet flushed to HBM.
        Public because writer backpressure (bench.py) and the solver's
        flush gate key off it — callers must not reach into
        ``_pending_rows`` (ADVICE r4)."""
        return sum(self._pending_rows) + self._prepared_rows

    def _staged_rows(self) -> int:
        """Rows still in staging (NOT counting pre-assembled planes) —
        the shard-aware drain's backlog signal: once a row is in a
        prepared plane there is no host work left, only the lockstep
        dispatch."""
        return sum(self._pending_rows)

    @property
    def steps_added(self) -> int:
        return sum(m.steps_added for m in self.slots)

    def _sampleable(self, slot: int) -> int:
        """Sampleable transition mass of a slot (0 until it can sample)."""
        m = self.slots[slot]
        window = m.stack + m.n_step + 1
        if len(m) <= window or m.valid_fraction() <= 0:
            return 0
        return len(m) - window

    def ready(self, learn_start: int) -> bool:
        """True when sampling can proceed: aggregate fill reached AND every
        shard has at least one slot with sampleable transitions (sample
        draws batch/D from *each* shard — SURVEY §7.3 item 6)."""
        if len(self) < learn_start:
            return False
        # multi-host: a process can only see (and fill) its local shards;
        # the cross-host AND happens at the caller (all_processes_ready)
        per_shard = {s: 0 for s in self.local_shards}
        for g in range(self.num_slots):
            if g % self.num_shards in per_shard:
                per_shard[g % self.num_shards] += self._sampleable(g)
        return all(mass > 0 for mass in per_shard.values())

    @property
    def beta(self) -> float:
        return beta_at(self._samples, self._cfg.priority_beta0,
                       self._cfg.priority_beta_steps)

    # -- write path ---------------------------------------------------------

    def _stage_rows(self, shard: int, idx: np.ndarray, cols: tuple) -> None:
        """Append one staged segment (in-shard offsets + payload columns)
        to the shard's staging backend. Columnar: one memcpy per column
        into the preallocated stage (``staged_append``); legacy: FIFO of
        array tuples. Callers hold the replay lock."""
        if self._columnar:
            if self._stages is None:
                self._stages = [None] * self.num_shards
            st = self._stages[shard]
            if st is None:
                from distributed_deep_q_tpu.replay.columnar import ColumnStage
                st = self._stages[shard] = ColumnStage(
                    [((), np.int32)] + list(self._stage_columns),
                    depth=self._staging_depth,
                    use_native=self._cfg.use_native)
            with tracing.span("staged_append"):
                st.append(idx, *cols)
        else:
            self._pending[shard].append((idx,) + tuple(cols))
        self._pending_rows[shard] += len(idx)

    def _stage(self, slot: int, local: np.ndarray, frames: np.ndarray) -> None:
        """Queue (slot-local rows, flat frames) for the HBM scatter and set
        their fresh-row priorities."""
        if self.prioritized:
            self.trees[slot].set(
                local, np.full(len(local),
                               self.max_priority ** self._cfg.priority_alpha))
        shard, base = self._slot_base(slot)
        self._stage_rows(shard, (base + local).astype(np.int32), (frames,))

    def add(self, frame, action, reward, done, boundary=None) -> int:
        """Single-stream add (in-process training loop)."""
        cycle = self._slot_cycle[0]
        slot = cycle[self._stream_pos[0] % len(cycle)]
        i = self.slots[slot].add(None, action, reward, done, boundary=boundary)
        self._stage(slot, np.asarray([i]),
                    np.asarray(frame, np.uint8).reshape(1, -1))
        if done if boundary is None else boundary:
            # episode finished → move this stream to its next slot, so one
            # stream eventually reaches every shard it owns
            self._stream_pos[0] += 1
        self._flush_or_notify()
        return int(self._global_index(slot, np.asarray(i)))

    def add_batch(self, batch, stream: int = 0) -> np.ndarray:
        """Contiguous chunk from one actor stream (RPC path). The chunk may
        contain episode boundaries; rows route to the stream's current slot,
        which advances at each boundary — so the chunk splits into
        boundary-delimited segments, each inserted with ONE vectorized
        metadata add + ONE priority-tree set + ONE staged frame block
        (per-row Python here was the measured config-4 ingest ceiling)."""
        assert 0 <= stream < self.num_streams, \
            f"stream {stream} outside configured num_streams={self.num_streams}"
        n = len(batch["action"])
        done = np.asarray(batch["done"], bool)
        boundary = np.asarray(batch.get("boundary", batch["done"]), bool)
        frames = np.ascontiguousarray(
            np.asarray(batch["frame"], np.uint8).reshape(n, -1))
        action = np.asarray(batch["action"])
        reward = np.asarray(batch["reward"])
        out = np.empty(n, np.int64)
        cuts = np.flatnonzero(boundary) + 1  # segment ends (exclusive)
        if len(cuts) == 0 or cuts[-1] != n:
            cuts = np.append(cuts, n)
        s0 = 0
        for s1 in cuts:
            cycle = self._slot_cycle[stream]
            slot = cycle[self._stream_pos[stream] % len(cycle)]
            m = self.slots[slot]
            # cap one metadata insert at slot_cap rows so a single call can
            # never wrap its own sub-ring (duplicate offsets in one scatter)
            for p0 in range(s0, s1, self.slot_cap):
                p1 = min(p0 + self.slot_cap, s1)
                li = m.add_batch({
                    "action": action[p0:p1], "reward": reward[p0:p1],
                    "done": done[p0:p1], "boundary": boundary[p0:p1]})
                self._stage(slot, li, frames[p0:p1])
                out[p0:p1] = self._global_index(slot, li)
            if boundary[s1 - 1]:
                self._stream_pos[stream] += 1
            s0 = s1
        self._flush_or_notify()
        return out

    def _flush_or_notify(self) -> None:
        """Chunk-boundary flush gate. With an ``IngestDrain`` attached
        the writer only nudges the drain thread (the work happens there,
        off this thread's lock hold); otherwise the legacy inline flush
        runs here. Multi-host the flush itself is deferred to the
        lockstep chunk boundary, but the drain still gets the nudge —
        its work there is host-only plane assembly (prepare_rounds)."""
        if max(self._pending_rows) < self.write_chunk:
            return
        if self._drain is not None:
            self._drain.notify()
        elif not self.defer_flush:
            self.flush()

    def start_drain(self, lock, min_rows: int | None = None):
        """Attach a background staging→device drain thread sharing
        ``lock`` (the caller's replay lock — mutual exclusion with
        writers and the sampler is unchanged). Returns the drain, or
        None when disabled by config.

        Multi-host meshes get a SHARD-AWARE drain (ISSUE 10): flushes
        there are lockstep collectives every process must enter at the
        same loop point, which a free-running thread cannot do — so the
        drain's work becomes ``prepare_rounds`` (host-only assembly of
        padded flush planes, zero collectives) keyed off the STAGED
        backlog, and the agreed-round flush at the chunk boundary only
        pops planes and dispatches. Same zero-copy columnar path as
        single-host, minus nothing."""
        if self._drain is not None:
            return self._drain
        if not self._drain_enabled:
            return None
        from distributed_deep_q_tpu.replay.columnar import IngestDrain
        min_r = min_rows or max(self.write_chunk, self._drain_min)
        if self.defer_flush:
            self._drain = IngestDrain(self, lock, min_r,
                                      work=self.prepare_rounds,
                                      backlog=self._staged_rows)
        else:
            self._drain = IngestDrain(self, lock, min_r)
        return self._drain

    def stop_drain(self) -> None:
        drain, self._drain = self._drain, None
        if drain is not None:
            drain.close()

    def reset_stream(self, stream: int) -> None:
        """Seal the stream's current slot at a writer identity change
        (actor restart reusing the stream id — SURVEY §5.3 recovery path):
        the slot's last written row gets a truncation boundary so no sampled
        stack or n-step window can straddle the dead actor's half-episode
        and the replacement's first episode."""
        if not (0 <= stream < self.num_streams):
            return
        cycle = self._slot_cycle[stream]
        slot = cycle[self._stream_pos[stream] % len(cycle)]
        self.slots[slot].seal_stream()

    def _flush_rounds_needed(self) -> int:
        backlog = -(-max((self._pending_rows[s] for s in self.local_shards),
                         default=0) // self.write_chunk)
        return len(self._prepared) + backlog

    def _assemble_round(self) -> tuple[np.ndarray, list, int]:
        """Build ONE padded write round from staging: ``write_chunk``
        lanes per LOCAL shard, shards with fewer pending rows padded
        with out-of-bounds indices the scatter drops. Pure host work (no
        device dispatch, no collective) — callable from the drain thread
        under the replay lock. Returns (idx, cols, rows_taken)."""
        k = self.write_chunk
        shards = self.local_shards
        dl = len(shards)
        idx = np.full((dl, k), self.cap_local, np.int32)  # OOB = drop
        cols = [np.zeros((dl, k) + tail, dt)
                for tail, dt in self._stage_columns]
        rows = 0
        for li, s in enumerate(shards):
            if self._columnar:
                st = (self._stages[s]
                      if self._stages is not None else None)
                if st is not None:
                    taken = st.take(k, [idx] + cols, li)
                    self._pending_rows[s] -= taken
                    rows += taken
                continue
            fill = 0
            while self._pending[s] and fill < k:
                entry = self._pending[s][0]
                i_arr = entry[0]
                take = min(len(i_arr), k - fill)
                idx[li, fill:fill + take] = i_arr[:take]
                for col, arr in zip(cols, entry[1:]):
                    col[li, fill:fill + take] = arr[:take]
                fill += take
                self._pending_rows[s] -= take
                rows += take
                if take == len(i_arr):
                    self._pending[s].pop(0)
                else:  # split the chunk, preserving FIFO write order
                    self._pending[s][0] = tuple(
                        a[take:] for a in entry)
        return idx, cols, rows

    def prepare_rounds(self, max_rounds: int | None = None) -> int:
        """Assemble staged rows into padded flush planes WITHOUT
        dispatching them — the shard-aware drain's work unit (ISSUE 10).
        Host-only, so it is safe from a free-running thread even on
        multi-host meshes where the dispatch itself is a lockstep
        collective; the planes go out FIFO-first at the next ``flush()``
        (the fused chunk boundary), so HBM write order is exactly staged
        order. Returns the number of rows moved into planes."""
        rounds = -(-max((self._pending_rows[s] for s in self.local_shards),
                        default=0) // self.write_chunk)
        if max_rounds is not None:
            rounds = min(rounds, int(max_rounds))
        total = 0
        for _ in range(rounds):
            plane = self._assemble_round()
            self._prepared.append(plane)
            self._prepared_rows += plane[2]
            total += plane[2]
        return total

    def flush(self) -> None:
        """Push all staged frames to HBM in fixed-shape chunks.

        Pre-assembled planes (``prepare_rounds``) dispatch first, then
        fresh rounds assemble from staging. Multi-host: the scatter is a
        global-array computation — a collective every process must enter
        the same number of times — so the round count is MAX-agreed
        across processes first (``global_max_int``) and short hosts
        dispatch all-padding chunks. Every process must therefore call
        ``flush()`` at the same loop point (the fused chunk boundary
        does; ingest defers via ``defer_flush``).
        """
        rounds = self._flush_rounds_needed()
        if self._pc > 1:
            from distributed_deep_q_tpu.parallel.multihost import (
                global_max_int)
            rounds = global_max_int(rounds)
        for _ in range(rounds):
            if self._prepared:
                idx, cols, rows = self._prepared.pop(0)
                self._prepared_rows -= rows
            else:
                idx, cols, _ = self._assemble_round()
            self._apply_write(idx, cols)

    def _apply_write(self, idx: np.ndarray, cols: list) -> None:
        """Dispatch one padded write chunk ([local_shards, k] planes) to
        the device ring. Subclasses with extra staged columns (device_per)
        override this to feed their wider scatter program."""
        d, k = self.num_shards, self.write_chunk
        assert len(self.local_shards) == d, (
            "DeviceFrameReplay's host-sample write path is "
            "single-controller; multi-host pixel runs use the fused "
            "DevicePERFrameReplay")
        self.ring = self._write(
            self.ring, idx.reshape(d * k),
            cols[0].reshape((d * k,) + self._stage_columns[0][0]))

    # -- sample path --------------------------------------------------------

    def _allocate(self, quota: int, masses: list[float]) -> list[int]:
        """Split ``quota`` draws across slots ∝ mass (largest remainder)."""
        return allocate_proportional(quota, masses)

    def sample(self, batch_size: int) -> dict[str, np.ndarray]:
        """Index batch (no pixels): per-shard draws concatenated in mesh
        order so ``P('dp')`` row-blocks land on the owning devices."""
        self.flush()
        d = self.num_shards
        per = batch_size // d
        parts: list[dict[str, np.ndarray]] = []
        self._samples += 1
        for s in range(d):
            shard_slots = [g for g in range(self.num_slots)
                           if g % d == s]
            if self.prioritized:
                masses = [self.trees[g].total if self._sampleable(g) else 0.0
                          for g in shard_slots]
            else:
                masses = [float(self._sampleable(g)) for g in shard_slots]
            counts = self._allocate(per, masses)
            assert sum(counts) == per, \
                f"shard {s} has no sampleable slot (gate on ready())"
            for g, c in zip(shard_slots, counts):
                if c == 0:
                    continue
                meta = self.slots[g]
                if self.prioritized:
                    local = sample_valid_from_tree(
                        self.trees[g], meta, c, self._rng)
                    p = self.trees[g].get(local)
                else:
                    local = meta.sample_indices(c)
                    p = np.ones(c)
                m = meta.gather_meta(local)
                _, base = self._slot_base(g)
                for key in ("oidx", "noidx"):
                    m[key] = (m[key] + base).astype(np.int32)
                m["index"] = self._global_index(g, local).astype(np.int64)
                m["_slot"] = np.full(c, g, np.int32)
                m["_p"] = p
                parts.append(m)
        batch = {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}

        if self.prioritized:
            # IS weights for the REALIZED stratified distribution: each
            # shard contributes exactly batch/D draws (proportional within
            # the shard), so P(i) = p_i / (D · mass_shard(i)) — using the
            # global mass would bias weights whenever shard masses differ.
            # Only SAMPLEABLE slots count: the allocation above zeroes
            # unsampleable ones, so their mass is not part of the realized
            # distribution either.
            shard_mass = np.zeros(d)
            for g in range(self.num_slots):
                if self._sampleable(g):
                    shard_mass[g % d] += self.trees[g].total
            owner_shard = batch.pop("_slot") % d
            n = len(self)
            pr = np.maximum(
                batch.pop("_p")
                / np.maximum(d * shard_mass[owner_shard], 1e-12), 1e-12)
            w = (n * pr) ** (-self.beta)
            batch["weight"] = (w / w.max()).astype(np.float32)
        else:
            batch.pop("_p")
            batch.pop("_slot")
            batch["weight"] = np.ones(batch_size, np.float32)
        batch["valid"] = batch["valid"].astype(np.uint8)
        batch["nvalid"] = batch["nvalid"].astype(np.uint8)
        batch["index"] = batch["index"].astype(np.int32)
        batch["_sampled_at"] = tuple(m.steps_added for m in self.slots)
        return batch

    # -- learner feedback ---------------------------------------------------

    def update_priorities(self, idx: np.ndarray, td_abs: np.ndarray,
                          sampled_at=None) -> None:
        if not self.prioritized:
            return
        gidx = np.asarray(idx, np.int64)
        td = np.abs(np.asarray(td_abs, np.float64)) + self._cfg.priority_eps
        slot_ids, local = self._slot_of_global(gidx)
        for g in np.unique(slot_ids):
            pick = slot_ids == g
            li, lt = local[pick], td[pick]
            if sampled_at is not None:
                li, lt = filter_stale(li, lt, self.slots[g].steps_added,
                                      sampled_at[g], self.slot_cap)
                if li.size == 0:
                    continue
            self.trees[g].set(li, lt ** self._cfg.priority_alpha)
            self.max_priority = max(self.max_priority, float(lt.max()))
