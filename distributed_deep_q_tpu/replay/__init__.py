from distributed_deep_q_tpu.replay.replay_memory import (  # noqa: F401
    ReplayMemory,
    FrameStackReplay,
)
