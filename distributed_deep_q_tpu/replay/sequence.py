"""Sequence replay for recurrent (R2D2) Q-learning — config 5 [M].

The reference has no sequence capability; the BASELINE.json config matrix
mandates "R2D2 recurrent (LSTM) Q-net, sequence replay" as the stretch
target. Design per Kapturowski et al. 2019:

- Fixed-length sequences of ``seq_len`` steps (``burn_in`` prefix + train
  window), stored with the **LSTM state at sequence start** (the
  "stored-state" strategy; staleness is tolerated because burn-in refreshes
  the carry before any gradient step — SURVEY §7.3 item 3).
- Adjacent sequences from one episode overlap by ``burn_in`` steps
  (R2D2's period = seq_len − burn_in emission schedule).
- Episode tails shorter than ``seq_len`` are zero-padded and masked; the
  mask also excludes burn-in steps from the loss (handled in the learner).
- Optional per-sequence PER with the R2D2 mixed max/mean |TD| priority
  (``ops/losses.sequence_dqn_loss``).

``SequenceBuilder`` is the actor-side window assembler: it tracks per-step
carries and emits ready sequences; ``SequenceReplay`` is the learner-side
store with the reference ``add``/``sample``/``__len__`` surface shape.
"""

from __future__ import annotations

from collections import deque
from typing import Any

import numpy as np

from distributed_deep_q_tpu.replay.prioritized import (
    SumTree, beta_at, filter_stale)


class SequenceReplay:
    """Ring buffer of fixed-length sequences with optional PER."""

    def __init__(
        self,
        capacity: int,
        seq_len: int,
        obs_shape: tuple[int, ...],
        obs_dtype=np.float32,
        lstm_size: int = 512,
        prioritized: bool = False,
        alpha: float = 0.9,
        beta0: float = 0.6,
        beta_steps: int = 1_000_000,
        eps: float = 1e-6,
        seed: int = 0,
        use_native: bool = True,
    ):
        self.capacity = int(capacity)
        self.seq_len = int(seq_len)
        t = self.seq_len
        self.obs = np.zeros((capacity, t + 1) + tuple(obs_shape), obs_dtype)
        self.action = np.zeros((capacity, t), np.int32)
        self.reward = np.zeros((capacity, t), np.float32)
        self.discount = np.zeros((capacity, t), np.float32)
        self.mask = np.zeros((capacity, t), np.float32)
        self.init_c = np.zeros((capacity, lstm_size), np.float32)
        self.init_h = np.zeros((capacity, lstm_size), np.float32)
        self._cursor = 0
        self._size = 0
        self._seqs_added = 0
        self._rng = np.random.default_rng(seed)

        self.prioritized = bool(prioritized)
        self.alpha, self.beta0 = float(alpha), float(beta0)
        self.beta_steps, self.eps = int(beta_steps), float(eps)
        self.tree = (SumTree(capacity, use_native=use_native)
                     if prioritized else None)
        self.max_priority = 1.0
        self._samples = 0

    def __len__(self) -> int:
        return self._size

    @property
    def steps_added(self) -> int:
        return self._seqs_added

    def ready(self, learn_start: int) -> bool:
        """``learn_start`` counts *sequences* in the recurrent pipeline."""
        return self._size >= max(learn_start, 1)

    @property
    def beta(self) -> float:
        return beta_at(self._samples, self.beta0, self.beta_steps)

    # -- write --------------------------------------------------------------

    def add_sequence(self, seq: dict[str, np.ndarray]) -> int:
        i = self._cursor
        self.obs[i] = seq["obs"]
        self.action[i] = seq["action"]
        self.reward[i] = seq["reward"]
        self.discount[i] = seq["discount"]
        self.mask[i] = seq["mask"]
        self.init_c[i] = seq["init_c"]
        self.init_h[i] = seq["init_h"]
        if self.prioritized:
            self.tree.set(np.asarray([i]),
                          np.asarray([self.max_priority ** self.alpha]))
        self._cursor = (i + 1) % self.capacity
        self._size = min(self._size + 1, self.capacity)
        self._seqs_added += 1
        return i

    def add_batch(self, batch: dict[str, np.ndarray]) -> np.ndarray:
        """Batch of sequences (RPC path): leading dim = sequence count."""
        n = len(batch["action"])
        return np.asarray([
            self.add_sequence({k: v[j] for k, v in batch.items()})
            for j in range(n)], np.int64)

    # -- sample -------------------------------------------------------------

    def sample(self, batch_size: int) -> dict[str, Any]:
        assert self._size > 0, "sample() from empty SequenceReplay"
        self._samples += 1
        if self.prioritized:
            idx = self.tree.sample_stratified(batch_size, self._rng)
            p = self.tree.get(idx)
            probs = np.maximum(p / max(self.tree.total, 1e-12), 1e-12)
            w = (self._size * probs) ** (-self.beta)
            weight = (w / w.max()).astype(np.float32)
        else:
            idx = self._rng.integers(0, self._size, size=batch_size)
            weight = np.ones(batch_size, np.float32)
        return {
            "obs": self.obs[idx],
            "action": self.action[idx],
            "reward": self.reward[idx],
            "discount": self.discount[idx],
            "mask": self.mask[idx],
            "init_c": self.init_c[idx],
            "init_h": self.init_h[idx],
            "weight": weight,
            "index": idx.astype(np.int32),
            "_sampled_at": self._seqs_added,
        }

    def update_priorities(self, idx: np.ndarray, priority: np.ndarray,
                          sampled_at: int | None = None) -> None:
        """Per-sequence priorities from the learner's mixed max/mean |TD|."""
        if not self.prioritized:
            return
        idx = np.asarray(idx, np.int64)
        p = np.abs(np.asarray(priority, np.float64)) + self.eps
        if sampled_at is not None:
            idx, p = filter_stale(idx, p, self._seqs_added, sampled_at,
                                  self.capacity)
            if idx.size == 0:
                return
        self.tree.set(idx, p ** self.alpha)
        self.max_priority = max(self.max_priority, float(p.max()))


class SequenceBuilder:
    """Actor-side sliding-window sequence assembler.

    Call ``on_step`` with each transition and the LSTM carry the policy held
    *before* consuming ``obs``; sequences of ``seq_len`` steps are emitted
    every ``seq_len − burn_in`` steps (overlapping windows) and at episode
    end (zero-padded + masked). The emitted dict matches
    ``SequenceReplay.add_sequence``.
    """

    def __init__(self, seq_len: int, burn_in: int,
                 obs_shape: tuple[int, ...], obs_dtype=np.float32,
                 lstm_size: int = 512, gamma: float = 0.99):
        assert 0 <= burn_in < seq_len
        self.seq_len, self.burn_in = int(seq_len), int(burn_in)
        self.period = self.seq_len - self.burn_in
        self.obs_shape, self.obs_dtype = tuple(obs_shape), obs_dtype
        self.lstm_size = int(lstm_size)
        self.gamma = float(gamma)
        # each entry: (obs, action, reward, done, (c, h) before the step)
        self._steps: deque = deque(maxlen=seq_len)
        self._since_emit = 0

    def reset(self) -> None:
        self._steps.clear()
        self._since_emit = 0

    def _emit(self, final_obs: np.ndarray) -> dict[str, np.ndarray]:
        t = self.seq_len
        n = len(self._steps)
        seq = {
            "obs": np.zeros((t + 1,) + self.obs_shape, self.obs_dtype),
            "action": np.zeros(t, np.int32),
            "reward": np.zeros(t, np.float32),
            "discount": np.zeros(t, np.float32),
            "mask": np.zeros(t, np.float32),
            "init_c": np.zeros(self.lstm_size, np.float32),
            "init_h": np.zeros(self.lstm_size, np.float32),
        }
        c, h = self._steps[0][4]
        seq["init_c"], seq["init_h"] = np.asarray(c), np.asarray(h)
        for j, (obs, a, r, done, _) in enumerate(self._steps):
            seq["obs"][j] = obs
            seq["action"][j] = a
            seq["reward"][j] = r
            seq["discount"][j] = 0.0 if done else self.gamma
            seq["mask"][j] = 1.0
        seq["obs"][n] = final_obs
        return seq

    def on_step(self, obs, action, reward, done: bool, carry,
                next_obs) -> list[dict[str, np.ndarray]]:
        """Returns emitted sequences (possibly empty). ``carry`` is the
        (c, h) the policy held before acting on ``obs``."""
        c, h = carry
        self._steps.append((np.asarray(obs), int(action), float(reward),
                            bool(done), (np.asarray(c).reshape(-1),
                                         np.asarray(h).reshape(-1))))
        self._since_emit += 1
        out = []
        if len(self._steps) == self.seq_len and (
                self._since_emit >= self.period or done):
            out.append(self._emit(np.asarray(next_obs)))
            self._since_emit = 0
        elif done and self._steps:
            out.append(self._emit(np.asarray(next_obs)))
            self._since_emit = 0
        if done:
            self._steps.clear()
        return out

    def flush_truncated(self, final_obs) -> list[dict[str, np.ndarray]]:
        """Emit the pending window at a time-limit truncation.

        Unlike termination, truncation keeps the bootstrap: the last step's
        discount stays γ and ``final_obs`` fills the bootstrap slot, so the
        tail of every truncated episode still reaches replay (the sequence
        analogue of ``NStepAccumulator.flush_truncated``). A no-op when the
        window was just emitted (nothing new since).
        """
        out = []
        if self._steps and self._since_emit > 0:
            out.append(self._emit(np.asarray(final_obs)))
        self._steps.clear()
        self._since_emit = 0
        return out
