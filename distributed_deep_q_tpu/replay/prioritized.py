"""Prioritized experience replay (PER) — config 3/4 capability [M].

The reference has uniform replay only; Double-DQN + PER is mandated by the
BASELINE.json config matrix ("Breakout, Double-DQN + prioritized replay").
Design follows Schaul et al. 2016 (proportional variant):

- Host-side **sum tree** over slot priorities (pointer-chasing → host, per
  SURVEY §7.3 item 2). The tree is a flat numpy array with fully vectorized
  batched set/sample (no Python per-element recursion); an optional C++ core
  (``native/``) replaces the descent loop when built.
- **Priorities** p = (|TD| + ε)^α set from the learner's per-sample ``td_abs``
  output each step — an async device→host round trip that never blocks the
  next train step (the learner returns |TD| as part of the step's outputs).
- **IS weights** w = (N·P(i))^-β / max_j w_j computed on host at sample time
  (cheap [B] math), annealing β → 1 over ``priority_beta_steps`` samples.

``PrioritizedReplay`` wraps either base buffer (``ReplayMemory`` or
``FrameStackReplay``) by composition: storage/gather semantics stay in the
base, prioritization owns only the index distribution. New slots enter at
max priority (optimistic: every transition is seen at least once).
"""

from __future__ import annotations

import numpy as np

from distributed_deep_q_tpu import native as _native


class SumTree:
    """Flat-array complete binary tree holding priorities in its leaves.

    ``size`` is the leaf count rounded up to a power of two; node ``i`` has
    children ``2i`` and ``2i+1``; leaves live at ``[size, 2*size)``; the
    total mass is at the root, index 1. All ops are batched numpy.
    """

    def __init__(self, capacity: int, use_native: bool = True):
        self.capacity = int(capacity)
        size = 1
        while size < capacity:
            size *= 2
        self.size = size
        self.tree = np.zeros(2 * size, np.float64)
        # C++ descent/set loops (native/replay_core.cpp) when buildable;
        # the numpy paths below remain the semantic reference
        self._native = _native.load() if use_native else None

    @property
    def total(self) -> float:
        return float(self.tree[1])

    def get(self, idx: np.ndarray) -> np.ndarray:
        return self.tree[np.asarray(idx) + self.size]

    def set(self, idx: np.ndarray, p: np.ndarray) -> None:
        """Set leaf priorities and repair all affected ancestors, level by
        level (duplicate indices resolve to the last write, like numpy)."""
        if self._native is not None:
            idx64 = np.ascontiguousarray(idx, np.int64)
            p64 = np.ascontiguousarray(p, np.float64)
            if idx64.size and (idx64.min() < 0 or idx64.max() >= self.size):
                raise IndexError(  # keep numpy's fail-fast, not a heap write
                    f"SumTree.set: index out of range [0, {self.size})")
            self._native.st_set(
                _native.as_double_p(self.tree), self.size,
                _native.as_int64_p(idx64), _native.as_double_p(p64),
                len(idx64))
            return
        leaf = np.asarray(idx, np.int64) + self.size
        self.tree[leaf] = p
        parents = np.unique(leaf >> 1)
        while parents.size and parents[0] >= 1:
            self.tree[parents] = (self.tree[2 * parents]
                                  + self.tree[2 * parents + 1])
            parents = np.unique(parents >> 1)
            if parents[0] == 0:
                parents = parents[1:]

    def sample_stratified(self, batch_size: int,
                          rng: np.random.Generator) -> np.ndarray:
        """Batched proportional sampling: one uniform draw per stratum of the
        total mass, then a vectorized root→leaf descent (all lanes descend a
        level per iteration — log₂(size) numpy steps, no Python recursion)."""
        total = self.tree[1]
        assert total > 0, "sample from empty SumTree"
        if self._native is not None:
            urand = np.ascontiguousarray(rng.random(batch_size))
            out = np.empty(batch_size, np.int64)
            self._native.st_sample_stratified(
                _native.as_double_p(self.tree), self.size,
                _native.as_double_p(urand), _native.as_int64_p(out),
                batch_size)
            return out
        targets = (np.arange(batch_size) + rng.random(batch_size)) \
            * (total / batch_size)
        idx = np.ones(batch_size, np.int64)
        while idx[0] < self.size:
            left = 2 * idx
            left_sum = self.tree[left]
            go_right = targets > left_sum
            targets -= left_sum * go_right
            idx = left + go_right
        return idx - self.size


def beta_at(samples: int, beta0: float, beta_steps: int) -> float:
    """IS-correction exponent annealed linearly β₀ → 1 over ``beta_steps``
    sample() calls (Schaul et al. §3.4)."""
    frac = min(samples / max(beta_steps, 1), 1.0)
    return beta0 + frac * (1.0 - beta0)


def filter_stale(idx: np.ndarray, vals: np.ndarray, steps_added: int,
                 sampled_at: int, capacity: int):
    """Drop (idx, vals) pairs whose ring slot was recycled by writes since
    the ``sampled_at`` snapshot.

    The write cursor of every ring here is ``steps_added % capacity``, so a
    slot is stale iff its distance ahead of the snapshot cursor is inside
    the since-written window. Returns filtered (idx, vals); both empty when
    a full buffer turnover happened. Shared by the host PER buffer, the
    device ring's per-slot trees, and the sequence replay.
    """
    written = steps_added - sampled_at
    if written <= 0:
        return idx, vals
    if written >= capacity:
        return idx[:0], vals[:0]
    cursor_then = sampled_at % capacity
    fresh = ((idx - cursor_then) % capacity) >= written
    return idx[fresh], vals[fresh]


def allocate_proportional(quota: int, masses: list[float]) -> list[int]:
    """Split ``quota`` integer draws across bins ∝ mass (largest remainder).
    Shared by the device ring's slot allocation and the host multi-stream
    replay. All-zero mass → all-zero counts."""
    total = sum(masses)
    if total <= 0:
        return [0] * len(masses)
    exact = [quota * m / total for m in masses]
    counts = [int(e) for e in exact]
    rem = quota - sum(counts)
    for i in sorted(range(len(exact)),
                    key=lambda i: exact[i] - counts[i], reverse=True)[:rem]:
        counts[i] += 1
    return counts


def sample_valid_from_tree(tree: SumTree, base, count: int,
                           rng: np.random.Generator) -> np.ndarray:
    """Proportional draw of ``count`` valid slot indices from ``tree``.

    Base-buffer validity (frame-stack window crossing the cursor,
    truncation-only boundaries): redraw invalid lanes through the tree a few
    times, then fall back to the base's uniform valid sampler. Shared by
    ``PrioritizedReplay`` and the device ring's per-slot trees.
    """
    idx = tree.sample_stratified(count, rng)
    invalid_fn = getattr(base, "_invalid", None)
    if invalid_fn is not None:
        bad = invalid_fn(idx)
        for _ in range(8):
            if not bad.any():
                break
            idx[bad] = tree.sample_stratified(int(bad.sum()), rng)
            bad = invalid_fn(idx)
        if bad.any():
            idx[bad] = base.sample_indices(int(bad.sum()))
    return idx


class PrioritizedReplay:
    """Proportional PER over any base buffer with add/gather/index surface.

    Exposes the reference ``ReplayMemory`` API (``add``/``add_batch``/
    ``sample``/``__len__`` [M]) plus ``update_priorities`` for the learner's
    per-step |TD| feedback.
    """

    prioritized = True

    def __init__(
        self,
        base,
        alpha: float = 0.6,
        beta0: float = 0.4,
        beta_steps: int = 1_000_000,
        eps: float = 1e-6,
        seed: int = 0,
        use_native: bool = True,
    ):
        self.base = base
        self.alpha = float(alpha)
        self.beta0 = float(beta0)
        self.beta_steps = int(beta_steps)
        self.eps = float(eps)
        self.tree = SumTree(base.capacity, use_native=use_native)
        self.max_priority = 1.0
        self._samples = 0
        self._rng = np.random.default_rng(seed)

    # -- reference-parity surface -----------------------------------------

    def __len__(self) -> int:
        return len(self.base)

    def ready(self, learn_start: int) -> bool:
        return self.base.ready(learn_start)

    @property
    def steps_added(self) -> int:
        return self.base.steps_added

    @property
    def beta(self) -> float:
        return beta_at(self._samples, self.beta0, self.beta_steps)

    def add(self, *args, **kwargs) -> int:
        i = self.base.add(*args, **kwargs)
        self.tree.set(np.asarray([i]),
                      np.asarray([self.max_priority ** self.alpha]))
        return i

    def add_batch(self, batch) -> np.ndarray:
        idx = self.base.add_batch(batch)
        self.tree.set(idx, np.full(len(idx), self.max_priority ** self.alpha))
        return idx

    def sample_indices_weighted(
            self, batch_size: int) -> tuple[np.ndarray, np.ndarray]:
        """(slot indices, unnormalized IS weights) — the index-distribution
        half of ``sample``, shared with the device-resident replay (which
        gathers pixels in HBM instead of through ``base.gather``)."""
        idx = sample_valid_from_tree(self.tree, self.base, batch_size,
                                     self._rng)
        self._samples += 1
        # IS weights: w_i = (N · P(i))^-β (Schaul et al. §3.4); callers
        # normalize by the batch max so updates only ever get scaled down.
        p = self.tree.get(idx)
        n = len(self.base)
        probs = np.maximum(p / max(self.tree.total, 1e-12), 1e-12)
        w = (n * probs) ** (-self.beta)
        return idx, w

    def sample(self, batch_size: int) -> dict[str, np.ndarray]:
        idx, w = self.sample_indices_weighted(batch_size)
        batch = self.base.gather(idx)
        batch["weight"] = (w / w.max()).astype(np.float32)
        batch["_sampled_at"] = self.base.steps_added
        return batch

    # -- learner feedback --------------------------------------------------

    def update_priorities(self, idx: np.ndarray, td_abs: np.ndarray,
                          sampled_at: int | None = None) -> None:
        """Write |TD|-derived priorities back to sampled slots.

        ``sampled_at`` is the buffer's ``steps_added`` snapshot taken when
        the batch was sampled; slots recycled by writes since then are
        dropped so a stale |TD| never clobbers a fresh transition's
        optimistic max-priority bootstrap (the cursor position is always
        ``steps_added % capacity``, so recency is decidable from counts).
        """
        idx = np.asarray(idx, np.int64)
        td = np.abs(np.asarray(td_abs, np.float64)) + self.eps
        if sampled_at is not None:
            idx, td = filter_stale(idx, td, self.base.steps_added,
                                   sampled_at, self.base.capacity)
            if idx.size == 0:
                return
        self.tree.set(idx, td ** self.alpha)
        self.max_priority = max(self.max_priority, float(td.max()))


def maybe_prioritize(base, cfg, seed: int = 0):
    """Wrap ``base`` in PER when ``cfg.prioritized`` (ReplayConfig) is set."""
    if not cfg.prioritized:
        return base
    return PrioritizedReplay(
        base, alpha=cfg.priority_alpha, beta0=cfg.priority_beta0,
        beta_steps=cfg.priority_beta_steps, eps=cfg.priority_eps, seed=seed,
        use_native=cfg.use_native)


class DelayedPriorityWriteback:
    """Priority write-back pipelined ``depth`` steps behind the learner.

    Reading per-sample |TD| back from the device is a D2H round trip; on a
    tunneled/remote TPU runtime that fetch measures ~70 ms even for 2 KB —
    done synchronously (even one step delayed) it caps a >1k steps/s
    learner at ~14 steps/s. Instead each pushed ``td_abs`` starts a
    non-blocking ``copy_to_host_async`` at dispatch time and is consumed
    only ``depth`` steps later, by which point the copy has landed and
    ``np.asarray`` is free. Priorities arrive ``depth`` grad-steps stale —
    well inside PER's tolerance (Ape-X applies learner-lagged updates from
    remote actors as a matter of design) — and ``filter_stale`` (via the
    replay's ``sampled_at`` snapshots) still drops updates for recycled
    rows exactly as in the synchronous path.

    ``to_host`` lets multi-host callers map the fetched array to their
    local rows (``multihost.local_rows``); default is a plain asarray.
    ``lock`` (e.g. the ReplayFeed server's ``replay_lock``) is held around
    each applied update when given.
    """

    def __init__(self, replay, depth: int = 8, to_host=None, lock=None):
        import contextlib
        from collections import deque

        self.replay = replay
        self.depth = max(int(depth), 1)
        self._to_host = to_host or (lambda x: np.asarray(x))
        self._lock = lock if lock is not None else contextlib.nullcontext()
        self._q: deque = deque()

    def push(self, index, td_abs, sampled_at) -> None:
        """Queue one step's (index, device |TD|, snapshot); applies the
        update that falls ``depth`` steps behind."""
        try:
            td_abs.copy_to_host_async()
        except AttributeError:
            pass  # non-jax array (already host-side)
        self._q.append((index, td_abs, sampled_at))
        if len(self._q) > self.depth:
            self._apply(self._q.popleft())

    def _apply(self, item) -> None:
        index, td_abs, sampled_at = item
        td = self._to_host(td_abs)  # fetch OUTSIDE the lock
        # positional: the second parameter is named td_abs on the
        # transition replays but priority on SequenceReplay
        with self._lock:
            self.replay.update_priorities(index, td, sampled_at=sampled_at)

    def drain(self) -> None:
        """Apply everything still queued (end of training / checkpoint)."""
        while self._q:
            self._apply(self._q.popleft())


def make_writeback(replay, replay_cfg, lock=None, to_host=None,
                   ) -> "DelayedPriorityWriteback":
    """The one constructor every training loop shares (single-process,
    distributed, recurrent): wires the config depth + optional server lock
    + optional multi-host row mapper."""
    return DelayedPriorityWriteback(
        replay, depth=replay_cfg.priority_writeback_delay,
        to_host=to_host, lock=lock)
