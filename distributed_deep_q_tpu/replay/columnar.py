"""Zero-copy columnar ingest staging + batched host→device drain.

ISSUE 8 tentpole. BENCH_r05 measured `ingest_transitions_per_s` at ~667
against a flagship sampler that wants ~300k rows/s — the ceiling was
per-flush Python, twice over: every staged segment allocated a fresh
dict-of-arrays tuple (O(segments) object churn on the `replay_lock` hot
path), and every chunk-boundary flush dispatched its device transfer
from whichever WRITER thread happened to cross the boundary, holding
the lock across the dispatch.

Two pieces replace that:

- ``ColumnStage`` — per-shard, per-column preallocated staging buffers.
  Decoded flush payloads land with ONE memcpy per column
  (``native/replay_core.cpp::staged_append``; the numpy slice-assign
  fallback is the bit-identical reference), and the flush drains
  contiguous column slices instead of walking a FIFO of tuples. Not
  thread-safe by itself: callers serialize appends and takes under the
  replay lock, exactly like the ``_pending`` FIFO it replaces.
- ``IngestDrain`` — a background transfer thread that batches staged
  columns into the device ring (`replay.flush()` under the shared
  lock) whenever a full write chunk is pending, so writer threads pay
  a cursor bump + condition notify and never the device dispatch.

The drain shares the caller's replay lock (same mutual exclusion as the
old inline flush — ``analysis/locks.py`` walks this file); its own
bookkeeping lives under ``_cv``.
"""

from __future__ import annotations

import threading

import numpy as np

from distributed_deep_q_tpu import native, tracing


class ColumnStage:
    """Preallocated columnar staging for one replay shard.

    ``columns`` is a list of ``(tail_shape, dtype)`` — column 0 is the
    in-shard row index, the rest are the replay's staged payload
    columns. Buffers grow by doubling (staged depth is a starting size,
    not a cap: the backpressure plane bounds occupancy in practice, and
    the legacy FIFO this replaces was unbounded too).
    """

    def __init__(self, columns, depth: int = 4096,
                 use_native: bool = True):
        self._columns = [(tuple(tail), np.dtype(dt)) for tail, dt in columns]
        self._depth = max(int(depth), 1)
        self._rows = 0
        self._bufs = [np.zeros((self._depth,) + tail, dt)
                      for tail, dt in self._columns]
        self._row_bytes = np.asarray(
            [dt.itemsize * int(np.prod(tail, dtype=np.int64))
             for tail, dt in self._columns], np.int64)
        self._lib = native.load() if use_native else None

    def __len__(self) -> int:
        return self._rows

    def _grow(self, need: int) -> None:
        while self._depth < need:
            self._depth *= 2
        grown = []
        for buf, (tail, dt) in zip(self._bufs, self._columns):
            new = np.zeros((self._depth,) + tail, dt)
            new[:self._rows] = buf[:self._rows]
            grown.append(new)
        self._bufs = grown

    def append(self, *cols) -> None:
        """Append one segment (same row count per column) at the cursor.

        Each column is coerced to its declared dtype/contiguity first so
        the native memcpy and the numpy fallback see identical bytes.
        """
        n = len(cols[0])
        if self._rows + n > self._depth:
            self._grow(self._rows + n)
        segs = [np.ascontiguousarray(c, dt).reshape((n,) + tail)
                for c, (tail, dt) in zip(cols, self._columns)]
        if self._lib is not None:
            self._rows = self._lib.staged_append(
                native.uint8_pp([native.as_uint8_p(b) for b in self._bufs]),
                native.uint8_pp([native.as_uint8_p(s) for s in segs]),
                native.as_int64_p(self._row_bytes), len(segs),
                self._rows, n)
        else:  # reference semantics — must stay bit-identical
            for buf, seg in zip(self._bufs, segs):
                buf[self._rows:self._rows + n] = seg
            self._rows += n

    def take(self, k: int, outs: list, li: int) -> int:
        """Drain up to ``k`` oldest rows into flush planes.

        ``outs[c][li, :take]`` receives column ``c``'s head; the
        remainder compacts to the front (FIFO order preserved, same as
        the legacy per-flush queue's split-preserving partial takes).
        """
        take = min(self._rows, k)
        if take == 0:
            return 0
        rem = self._rows - take
        for out, buf in zip(outs, self._bufs):
            out[li, :take] = buf[:take]
            if rem:
                buf[:rem] = buf[take:self._rows]
        self._rows = rem
        return take


class IngestDrain:
    """Batched host→device transfer thread for a device replay ring.

    Waits until the backlog reaches ``min_rows``, then runs the work
    unit under the SHARED replay lock — one traced ``ingest_drain``
    hold per batch, off the writer threads. Writers call ``notify()``
    (cheap) instead of flushing inline.

    The work unit is pluggable (ISSUE 10's shard-aware multi-host
    drain): by default it is ``replay.flush()`` (the full host→device
    dispatch) with the staged-row delta as its progress count; a
    multi-host ring instead passes ``work=prepare_rounds`` (host-only
    plane assembly — the dispatch there is a lockstep collective the
    solver enters at the chunk boundary) and ``backlog=_staged_rows``
    so prepared planes stop re-triggering the thread. ``work`` returns
    the rows it moved; counters and lock discipline are identical in
    both modes.
    """

    def __init__(self, replay, lock, min_rows: int, poll_s: float = 0.05,
                 work=None, backlog=None):
        self._replay = replay
        self._lock = lock
        self._min = max(int(min_rows), 1)
        self._poll_s = float(poll_s)
        self._work = work
        self._backlog = backlog if backlog is not None \
            else replay.pending_rows
        self._cv = threading.Condition()
        self._stop = False
        self._drained_rows = 0
        self._drain_flushes = 0
        self._err: BaseException | None = None
        self._thread = threading.Thread(
            target=self._run, name="ingest-drain", daemon=True)
        self._thread.start()

    def notify(self) -> None:
        with self._cv:
            self._cv.notify()

    def counters(self) -> dict[str, int]:
        with self._cv:
            if self._err is not None:
                raise RuntimeError("ingest drain thread died") from self._err
            return {"rows": self._drained_rows,
                    "flushes": self._drain_flushes}

    def _do_work(self) -> int:
        """One work unit under the replay lock; returns rows moved."""
        if self._work is not None:
            return int(self._work())
        before = self._replay.pending_rows()
        self._replay.flush()
        return before - self._replay.pending_rows()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._stop and self._backlog() < self._min:
                    self._cv.wait(timeout=self._poll_s)
                if self._stop:
                    return
            try:
                with tracing.locked(self._lock):
                    with tracing.span("ingest_drain"):
                        drained = self._do_work()
            except BaseException as e:  # surfaced on counters()/close()
                with self._cv:
                    self._err = e
                return
            with self._cv:
                self._drained_rows += drained
                self._drain_flushes += 1

    def close(self) -> None:
        """Stop the thread; run one final work unit under the lock (so
        no staged rows are stranded below the chunk threshold — for the
        multi-host variant this only assembles planes, the lockstep
        flush dispatches them), then re-raise a death the thread
        recorded."""
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._thread.join(timeout=10)
        with tracing.locked(self._lock):
            self._do_work()
        with self._cv:
            if self._err is not None:
                raise RuntimeError("ingest drain thread died") from self._err
