"""Host→device staging — double-buffered batch prefetch (SURVEY.md §7.3
item 1: "keeping a pmap'd learner fed from a Python replay buffer …
double-buffering, avoiding device_put stalls is where the 50× target is won
or lost").

The reference ships minibatches across a Python↔Caffe process boundary every
step (barista-style shmem + sockets, SURVEY §2 "IPC / shared-memory glue"
[R]). The TPU equivalent of that glue is ``jax.device_put`` onto the mesh's
batch sharding — and hiding its latency: a background thread keeps a small
queue of batches already resident on device, so the learner's ``get()``
returns a device batch that was transferred while the previous step was
computing.

Host-only bookkeeping keys (``index``, ``_sampled_at``) ride along
untransferred so PER priority write-back still works. Depth 2 is true double
buffering: one batch being consumed, one in flight.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable

import jax

from distributed_deep_q_tpu import tracing

HOST_KEYS = ("index", "_sampled_at")


class DeviceStager:
    """Background sampler → device transfer pipeline.

    ``sample_fn()`` produces a host batch dict; batches appear on the
    internal queue already ``device_put`` to ``sharding`` (host-only keys
    kept as numpy). Call ``get()`` in the learner loop; ``close()`` joins
    the thread. The queue is bounded (``depth``), so sampling backpressures
    when the learner falls behind rather than buffering stale batches —
    this bounds PER priority staleness to ``depth`` steps.
    """

    def __init__(self, sample_fn: Callable[[], dict[str, Any]],
                 sharding=None, depth: int = 2,
                 lock: threading.Lock | None = None):
        """``lock`` serializes ``sample_fn`` against writers that mutate the
        same replay from other threads (PER ``update_priorities``, RPC
        ``add_batch``) — the SumTree is not internally synchronized, so PER
        callers MUST pass the lock they use for priority write-back."""
        self._sample_fn = sample_fn
        self._sharding = sharding
        self._lock = lock if lock is not None else threading.Lock()
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._err: BaseException | None = None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="replay-stager")
        self._thread.start()

    def _stage(self, batch: dict[str, Any]) -> dict[str, Any]:
        with tracing.span("stage_batch"):
            host = {k: batch.pop(k) for k in HOST_KEYS if k in batch}
            with tracing.span("device_put"):
                if self._sharding is not None:
                    dev = jax.device_put(batch, self._sharding)
                else:
                    dev = jax.device_put(batch)
            dev.update(host)
            return dev

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                # lock_wait (contention) and sample (work under the
                # lock) surface as separate stages in the attribution
                with tracing.locked(self._lock):
                    with tracing.span("sample"):
                        batch = self._sample_fn()
                staged = self._stage(batch)
                while not self._stop.is_set():
                    try:
                        self._q.put(staged, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # surfaced on the consumer's next get()
            self._err = e

    @property
    def lock(self) -> threading.Lock:
        """The sampler lock; hold it for any replay mutation (priority
        write-back, adds) done outside this stager's thread."""
        return self._lock

    def get(self, timeout: float = 30.0) -> dict[str, Any]:
        """Next device-resident batch (blocks until the pipeline has one)."""
        deadline = timeout
        while True:
            if self._err is not None:
                raise RuntimeError("staging thread failed") from self._err
            try:
                return self._q.get(timeout=min(deadline, 0.5))
            except queue.Empty:
                deadline -= 0.5
                if deadline <= 0:
                    raise TimeoutError(
                        "DeviceStager.get(): no batch produced in time")

    def close(self) -> None:
        self._stop.set()
        # drain so a blocked put() can observe the stop flag
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)
