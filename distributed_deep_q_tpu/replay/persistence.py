"""Optional replay persistence — npz dump/load of every replay tier.

SURVEY.md §5.4: the reference family optionally persisted the replay buffer
(HDF5-backed variant [R]); the rebuild's default stays warm-refill (matching
reference behavior), and this module supplies the opt-in persistence behind
``ReplayConfig.persist_path``. One ``.npz`` file carries the complete
sampling state of a buffer — ring contents, cursors, priority trees, the
β-anneal counter, and the numpy RNG states — so a restored buffer's next
``sample()`` is byte-identical to what the saved one would have drawn
(tests/test_persistence.py proves exactly that).

Device-resident tiers (``DeviceFrameReplay`` / ``DevicePERFrameReplay``)
download their HBM rings once at save (``np.asarray`` on the sharded array
assembles the global view) and re-upload with the mesh sharding at load —
persistence is a cold-path operation; nothing here touches the train step.

Format: flat npz keys. Scalars ride as 0-d arrays; RNG states as JSON
strings. ``meta_kind`` + geometry keys guard against loading a file into a
mismatched buffer.
"""

from __future__ import annotations

import json
import os

import numpy as np

from distributed_deep_q_tpu.utils.durability import atomic_write, savez_bytes

SCHEMA = 1


# -- rng state (json round-trip keeps npz dtype-clean) -----------------------


def _rng_dump(rng: np.random.Generator) -> str:
    return json.dumps(rng.bit_generator.state)


def _rng_load(rng: np.random.Generator, s: str) -> None:
    rng.bit_generator.state = json.loads(s)


def _str(v) -> str:
    """npz round-trips str as 0-d ``<U`` arrays."""
    return str(np.asarray(v)[()]) if not isinstance(v, str) else v


# -- per-tier (de)serializers -------------------------------------------------


def _frame_stack_state(m, prefix: str) -> dict:
    d = {
        f"{prefix}action": m.action, f"{prefix}reward": m.reward,
        f"{prefix}done": m.done, f"{prefix}boundary": m.boundary,
        f"{prefix}cursor": m._cursor, f"{prefix}size": m._size,
        f"{prefix}steps_added": m._steps_added,
        f"{prefix}rng": _rng_dump(m._rng),
    }
    if m.frames is not None:
        d[f"{prefix}frames"] = m.frames
    return d


def _frame_stack_restore(m, z, prefix: str) -> None:
    assert int(z[f"{prefix}size"]) <= m.capacity, "capacity shrank under file"
    m.action[:] = z[f"{prefix}action"]
    m.reward[:] = z[f"{prefix}reward"]
    m.done[:] = z[f"{prefix}done"]
    m.boundary[:] = z[f"{prefix}boundary"]
    m._cursor = int(z[f"{prefix}cursor"])
    m._size = int(z[f"{prefix}size"])
    m._steps_added = int(z[f"{prefix}steps_added"])
    _rng_load(m._rng, _str(z[f"{prefix}rng"]))
    if m.frames is not None:
        m.frames[:] = z[f"{prefix}frames"]


_SEQ_META = ("action", "reward", "discount", "mask", "init_c", "init_h")


def _owned(d: dict) -> dict:
    """Snapshot isolation for a captured state dict: copy host-resident
    array views so the caller can serialize off-lock while the replay
    keeps mutating. ``dev_*`` keys are fresh HBM downloads (np.asarray
    of device arrays) and already owned."""
    return {k: np.array(v) if isinstance(v, np.ndarray)
            and not k.startswith("dev_") else v
            for k, v in d.items()}


def save_replay(replay, path: str) -> None:
    """Dump ``replay``'s complete sampling state to ``path`` atomically
    (tmp + fsync + rename — ``np.savez`` straight to the final path
    leaves a torn file on crash). Mirrors np.savez's historical naming:
    ``.npz`` is appended when ``path`` lacks it."""
    path = os.fspath(path)
    if not path.endswith(".npz"):
        path += ".npz"
    atomic_write(path, savez_bytes(**replay_state(replay)))


def replay_state(replay) -> dict:
    """Capture ``replay``'s complete sampling state as a flat dict (the
    npz key space of ``save_replay``). Every array is owned by the
    result — callers holding ``replay_lock`` can capture briefly and
    serialize/fsync after releasing it."""
    from distributed_deep_q_tpu.replay.device_per import DevicePERFrameReplay
    from distributed_deep_q_tpu.replay.device_ring import DeviceFrameReplay
    from distributed_deep_q_tpu.replay.device_sequence import (
        DeviceSequenceReplay)
    from distributed_deep_q_tpu.replay.prioritized import PrioritizedReplay
    from distributed_deep_q_tpu.replay.replay_memory import (
        FrameStackReplay, ReplayMemory)
    from distributed_deep_q_tpu.replay.sequence import SequenceReplay

    d: dict = {"meta_schema": SCHEMA}

    if isinstance(replay, SequenceReplay):
        d["meta_kind"] = "sequence"
        d["meta_capacity"] = replay.capacity
        d["meta_seq_len"] = replay.seq_len
        for k in _SEQ_META + ("obs",):
            d[k] = getattr(replay, k)
        d["cursor"] = replay._cursor
        d["size"] = replay._size
        d["seqs_added"] = replay._seqs_added
        d["samples"] = replay._samples
        d["max_priority"] = replay.max_priority
        d["rng"] = _rng_dump(replay._rng)
        if replay.prioritized:
            d["tree"] = replay.tree.tree
        return _owned(d)

    if isinstance(replay, DeviceSequenceReplay):
        replay.flush()  # staged sequences must be in the state we dump
        d["meta_kind"] = "device_sequence"
        d["meta_capacity"] = replay.capacity
        d["meta_seq_len"] = replay.seq_len
        d["meta_W"] = replay.W
        for k in _SEQ_META + ("n_valid",):
            d[k] = getattr(replay, k)
        d["cursor"] = replay._cursor
        d["sizes"] = replay._sizes
        d["added"] = replay._added
        d["next_shard"] = replay._next_shard
        d["seqs_added"] = replay._seqs_added
        d["samples"] = replay._samples
        d["max_priority"] = replay.max_priority
        d["rng"] = _rng_dump(replay._rng)
        if replay.prioritized:
            for i, t in enumerate(replay.trees):
                d[f"tree{i}"] = t.tree
        d["dev_ring"] = np.asarray(replay.ring)
        for k, v in replay.dmeta.items():
            d[f"dev_{k}"] = np.asarray(v)
        d["dev_maxp"] = np.asarray(replay.dmaxp)
        return _owned(d)

    if isinstance(replay, PrioritizedReplay):
        d["meta_kind"] = "prioritized"
        d["tree"] = replay.tree.tree
        d["max_priority"] = replay.max_priority
        d["samples"] = replay._samples
        d["per_rng"] = _rng_dump(replay._rng)
        base, inner = replay.base, "base_"
    else:
        base, inner = replay, ""

    if isinstance(replay, DeviceFrameReplay):  # incl. DevicePERFrameReplay
        replay.flush()  # staged rows must be in the device state we dump
        d["meta_kind"] = ("device_per" if isinstance(replay,
                                                     DevicePERFrameReplay)
                         else d.get("meta_kind", "device_ring"))
        d["meta_capacity"] = replay.capacity
        d["meta_num_slots"] = replay.num_slots
        d["meta_num_streams"] = replay.num_streams
        d["stream_pos"] = np.asarray(replay._stream_pos, np.int64)
        d["max_priority"] = replay.max_priority
        d["samples"] = replay._samples
        d["ring_rng"] = _rng_dump(replay._rng)
        for i, m in enumerate(replay.slots):
            d.update(_frame_stack_state(m, f"slot{i}_"))
        if isinstance(replay, DevicePERFrameReplay):
            for k in ("frames", "action", "reward", "done", "boundary",
                      "prio", "maxp"):
                d[f"dev_{k}"] = np.asarray(getattr(replay.dstate, k))
        else:
            d["dev_frames"] = np.asarray(replay.ring)
            if replay.prioritized:
                for i, t in enumerate(replay.trees):
                    d[f"tree{i}"] = t.tree
    elif isinstance(base, FrameStackReplay):
        d.setdefault("meta_kind", "frame_stack")
        d["meta_capacity"] = base.capacity
        d.update(_frame_stack_state(base, inner))
    elif isinstance(base, ReplayMemory):
        d.setdefault("meta_kind", "memory")
        d["meta_capacity"] = base.capacity
        d.update({
            f"{inner}obs": base.obs, f"{inner}next_obs": base.next_obs,
            f"{inner}action": base.action, f"{inner}reward": base.reward,
            f"{inner}discount": base.discount,
            f"{inner}cursor": base._cursor, f"{inner}size": base._size,
            f"{inner}steps_added": base._steps_added,
            f"{inner}rng": _rng_dump(base._rng),
        })
    else:
        raise TypeError(f"no persistence for {type(replay).__name__}")
    return _owned(d)


def load_replay(replay, path: str) -> None:
    """Restore state saved by ``save_replay`` into a freshly constructed,
    geometry-matched ``replay`` (same class, capacity, slot layout)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_deep_q_tpu.parallel.mesh import AXIS_DP
    from distributed_deep_q_tpu.replay.device_per import DevicePERFrameReplay
    from distributed_deep_q_tpu.replay.device_ring import DeviceFrameReplay
    from distributed_deep_q_tpu.replay.device_sequence import (
        DeviceSequenceReplay)
    from distributed_deep_q_tpu.replay.prioritized import PrioritizedReplay
    from distributed_deep_q_tpu.replay.replay_memory import (
        FrameStackReplay, ReplayMemory)
    from distributed_deep_q_tpu.replay.sequence import SequenceReplay

    z = np.load(path, allow_pickle=False)
    kind = _str(z["meta_kind"])

    if isinstance(replay, SequenceReplay):
        assert kind == "sequence", f"file holds {kind!r}"
        assert int(z["meta_capacity"]) == replay.capacity and \
            int(z["meta_seq_len"]) == replay.seq_len, "geometry mismatch"
        assert ("tree" in z) == replay.prioritized, (
            "prioritized-ness mismatch: file was saved with prioritized="
            f"{'tree' in z}, buffer is prioritized={replay.prioritized}")
        assert z["obs"].shape == replay.obs.shape and \
            z["obs"].dtype == replay.obs.dtype, "obs store mismatch"
        for k in _SEQ_META + ("obs",):
            getattr(replay, k)[:] = z[k]
        replay._cursor = int(z["cursor"])
        replay._size = int(z["size"])
        replay._seqs_added = int(z["seqs_added"])
        replay._samples = int(z["samples"])
        replay.max_priority = float(z["max_priority"])
        _rng_load(replay._rng, _str(z["rng"]))
        if replay.prioritized:
            t = replay.tree
            t.set(np.arange(t.size), z["tree"][t.size: 2 * t.size])
        return

    if isinstance(replay, DeviceSequenceReplay):
        assert kind == "device_sequence", f"file holds {kind!r}"
        assert int(z["meta_capacity"]) == replay.capacity and \
            int(z["meta_seq_len"]) == replay.seq_len and \
            int(z["meta_W"]) == replay.W, "geometry mismatch"
        assert ("tree0" in z) == replay.prioritized, (
            "prioritized-ness mismatch: file was saved with prioritized="
            f"{'tree0' in z}, buffer is prioritized={replay.prioritized}")
        assert z["dev_ring"].shape == replay.ring.shape and \
            z["dev_ring"].dtype == replay.ring.dtype, (
            "pixel-plane layout mismatch (saved by an incompatible "
            "version)")
        for k in _SEQ_META + ("n_valid",):
            getattr(replay, k)[:] = z[k]
        replay._cursor[:] = z["cursor"]
        replay._sizes[:] = z["sizes"]
        replay._added[:] = z["added"]
        replay._next_shard = int(z["next_shard"])
        replay._seqs_added = int(z["seqs_added"])
        replay._samples = int(z["samples"])
        replay.max_priority = float(z["max_priority"])
        _rng_load(replay._rng, _str(z["rng"]))
        if replay.prioritized:
            for i, t in enumerate(replay.trees):
                t.set(np.arange(t.size), z[f"tree{i}"][t.size: 2 * t.size])
        sharded = NamedSharding(replay.mesh, P(AXIS_DP))
        replay.ring = jax.device_put(z["dev_ring"], sharded)
        replay.dmeta = {k: jax.device_put(z[f"dev_{k}"], sharded)
                        for k in replay.dmeta}
        replay.dmaxp = jax.device_put(z["dev_maxp"],
                                      NamedSharding(replay.mesh, P()))
        return

    if isinstance(replay, PrioritizedReplay):
        assert kind == "prioritized", f"file holds {kind!r}"
        replay.tree.set(np.arange(replay.tree.size),
                        z["tree"][replay.tree.size:
                                  replay.tree.size + replay.tree.size])
        replay.max_priority = float(z["max_priority"])
        replay._samples = int(z["samples"])
        _rng_load(replay._rng, _str(z["per_rng"]))
        base, inner = replay.base, "base_"
    else:
        base, inner = replay, ""

    if isinstance(replay, DeviceFrameReplay):
        expect = ("device_per" if isinstance(replay, DevicePERFrameReplay)
                  else "device_ring")
        assert kind == expect, f"file holds {kind!r}, buffer is {expect!r}"
        assert int(z["meta_capacity"]) == replay.capacity and \
            int(z["meta_num_slots"]) == replay.num_slots, \
            "ring geometry mismatch (capacity / slot layout)"
        replay._stream_pos = [int(v) for v in z["stream_pos"]]
        replay.max_priority = float(z["max_priority"])
        replay._samples = int(z["samples"])
        _rng_load(replay._rng, _str(z["ring_rng"]))
        for i, m in enumerate(replay.slots):
            _frame_stack_restore(m, z, f"slot{i}_")
        sharded = NamedSharding(replay.mesh, P(AXIS_DP))
        if isinstance(replay, DevicePERFrameReplay):
            # frame-plane format guard: the round-5 ring is flat padded
            # int32 (ghost rows, DMA layout) — a file from the old 2-D
            # uint8 layout has matching capacity/slots but would fail
            # deep inside shard_map on the next dispatch
            want = replay.dstate.frames
            got = z["dev_frames"]
            assert got.shape == want.shape and got.dtype == want.dtype, (
                f"frame-plane layout mismatch: file has {got.dtype}"
                f"{got.shape}, buffer expects {want.dtype}{want.shape} "
                "(saved by an incompatible version)")
            replicated = NamedSharding(replay.mesh, P())
            replay.dstate = replay.dstate.replace(**{
                k: jax.device_put(z[f"dev_{k}"],
                                  replicated if k == "maxp" else sharded)
                for k in ("frames", "action", "reward", "done", "boundary",
                          "prio", "maxp")})
            replay._di_cache = None
        else:
            replay.ring = jax.device_put(z["dev_frames"], sharded)
            if replay.prioritized:
                for i, t in enumerate(replay.trees):
                    t.set(np.arange(t.size), z[f"tree{i}"][t.size: 2 * t.size])
    elif isinstance(base, FrameStackReplay):
        assert int(z["meta_capacity"]) == base.capacity, "capacity mismatch"
        _frame_stack_restore(base, z, inner)
    elif isinstance(base, ReplayMemory):
        assert int(z["meta_capacity"]) == base.capacity, "capacity mismatch"
        base.obs[:] = z[f"{inner}obs"]
        base.next_obs[:] = z[f"{inner}next_obs"]
        base.action[:] = z[f"{inner}action"]
        base.reward[:] = z[f"{inner}reward"]
        base.discount[:] = z[f"{inner}discount"]
        base._cursor = int(z[f"{inner}cursor"])
        base._size = int(z[f"{inner}size"])
        base._steps_added = int(z[f"{inner}steps_added"])
        _rng_load(base._rng, _str(z[f"{inner}rng"]))
    else:
        raise TypeError(f"no persistence for {type(replay).__name__}")
