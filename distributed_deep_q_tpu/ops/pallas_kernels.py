"""Pallas TPU kernels — the fused masked-Huber TD loss.

The reference's loss math lives inside Caffe's C++/CUDA layers (SURVEY.md §1
L0 [P][R]); the rebuilt compute path normally leans on XLA to fuse
``ops/losses.py`` into the matmul epilogues. This module is the
hand-scheduled alternative for the loss tail: ONE VMEM-resident kernel that
fuses the action gather (one-hot contraction), TD residual, Huber, and the
importance-weighted mean — plus a matching hand-written backward kernel so
the whole loss is a single fused region in both directions
(``jax.custom_vjp``).

Enabled with ``TrainConfig.use_pallas_loss``; the learner falls back to the
jnp path otherwise (both are tested for equivalence in
``tests/test_pallas.py``). On non-TPU backends the kernel runs in Pallas
interpret mode so the same code path is testable on the CPU mesh.

Shapes are the per-device view inside ``shard_map``: ``q`` is [B, A] with B
the per-device batch. Everything fits in VMEM by construction (B ≤ a few
hundred, A ≤ 18), so there is no grid — one program, full blocks, which is
exactly the right schedule for a loss tail this small.

MEASUREMENT: bench.py times this kernel against the XLA-fused jnp path
every run (``pallas_on_steps_per_s`` vs ``pallas_off_steps_per_s``) so the
claim is re-made per hardware, not asserted here — early v5e runs landed
on both sides of parity depending on chip contention, i.e. the two paths
are close (XLA already fuses this loss tail well; SURVEY §2.1's "Pallas
only where XLA fusion is insufficient" holds in the sense that neither
side is decisively faster). The kernel ships default OFF
(``use_pallas_loss=False``) as the tested hand-written-kernel path;
consult the current BENCH json before flipping the default.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    """Compile for real TPUs, interpret everywhere else (CPU test mesh)."""
    return jax.default_backend() != "tpu"


def _huber_pieces(td: jax.Array, delta: float):
    abs_td = jnp.abs(td)
    quad = jnp.minimum(abs_td, delta)
    return abs_td, 0.5 * quad * quad + delta * (abs_td - quad)


def _fwd_kernel(q_ref, a_ref, t_ref, w_ref, loss_ref, td_ref, *, delta: float):
    """loss = mean_b w_b · huber(q[b, a_b] − t_b); td_ref = |TD| per sample."""
    q = q_ref[:]                                            # [B, A]
    col = jax.lax.broadcasted_iota(jnp.int32, q.shape, 1)   # [B, A]
    onehot = (col == a_ref[:]).astype(q.dtype)              # a_ref: [B, 1]
    q_sa = jnp.sum(q * onehot, axis=1, keepdims=True)       # [B, 1]
    td = q_sa - t_ref[:]
    abs_td, hub = _huber_pieces(td, delta)
    loss_ref[0, 0] = jnp.mean(w_ref[:] * hub)
    td_ref[:] = abs_td


def _bwd_kernel(q_ref, a_ref, t_ref, w_ref, g_ref, dq_ref, *, delta: float):
    """dL/dq[b, a] = g · w_b · huber'(TD_b) / B at a = a_b, else 0.

    huber'(x) = clip(x, −delta, +delta) — recomputing TD here is cheaper
    than round-tripping it through HBM (free recompute vs. bandwidth).
    """
    q = q_ref[:]
    col = jax.lax.broadcasted_iota(jnp.int32, q.shape, 1)
    onehot = (col == a_ref[:]).astype(q.dtype)
    q_sa = jnp.sum(q * onehot, axis=1, keepdims=True)
    td = q_sa - t_ref[:]
    dhub = jnp.clip(td, -delta, delta)
    coeff = g_ref[0, 0] * w_ref[:] * dhub / q.shape[0]      # [B, 1]
    dq_ref[:] = onehot * coeff


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_dqn_loss(q, actions, targets, weights, delta: float = 1.0):
    """Fused masked-Huber TD loss (Pallas). Same contract as
    ``ops.losses.dqn_loss``: returns (scalar loss, |TD| [B]).

    ``targets``/``weights`` are treated as constants (no gradient), matching
    the stop-gradient semantics of the jnp path.
    """
    loss, td_abs = _call_fwd(q, actions, targets, weights, delta)
    return loss, td_abs


def _call_fwd(q, actions, targets, weights, delta):
    b, _ = q.shape
    a2 = actions.astype(jnp.int32).reshape(b, 1)
    t2 = targets.astype(q.dtype).reshape(b, 1)
    w2 = weights.astype(q.dtype).reshape(b, 1)
    loss, td = pl.pallas_call(
        functools.partial(_fwd_kernel, delta=float(delta)),
        out_shape=(
            jax.ShapeDtypeStruct((1, 1), q.dtype),
            jax.ShapeDtypeStruct((b, 1), q.dtype),
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 4,
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        interpret=_interpret(),
    )(q, a2, t2, w2)
    return loss[0, 0], td[:, 0]


def _fwd_rule(q, actions, targets, weights, delta):
    out = _call_fwd(q, actions, targets, weights, delta)
    return out, (q, actions, targets, weights)


def _bwd_rule(delta, residuals, cotangents):
    q, actions, targets, weights = residuals
    g_loss, _ = cotangents  # td_abs output carries no gradient (|TD| is
    #                         stop-gradient by contract, like the jnp path)
    b, _ = q.shape
    a2 = actions.astype(jnp.int32).reshape(b, 1)
    t2 = targets.astype(q.dtype).reshape(b, 1)
    w2 = weights.astype(q.dtype).reshape(b, 1)
    g2 = jnp.asarray(g_loss, q.dtype).reshape(1, 1)
    dq = pl.pallas_call(
        functools.partial(_bwd_kernel, delta=float(delta)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 4
        + [pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(q, a2, t2, w2, g2)
    # int actions take a float0 cotangent; targets/weights are constants
    da = np.zeros(actions.shape, jax.dtypes.float0)
    return dq, da, jnp.zeros_like(targets), jnp.zeros_like(weights)


fused_dqn_loss.defvjp(_fwd_rule, _bwd_rule)
