"""Pallas row-DMA kernels for the 1-D linear frame ring (round 5).

Why these exist — measured XLA:TPU gather pathology on the fused PER path
(scripts/sample_ablate.py, 1M-frame ring, chain=32 × batch 512):

- a row gather from a tiled ``uint8 [cap, 7056]`` ring reads whole
  (32, 128) tiles per requested row — ~32× the wanted bytes; the two
  obs/next-obs gathers measured ~44 ms/chunk (~20 GB/s useful).
- a slice-gather of multi-row windows compiles to a lane-padded
  ``[N, W, row]`` temp (16× expansion → 13.8 GB → compile OOM), and
  Mosaic rejects sublane-unaligned HBM slices for DMA.

The fix is layout, not lowering: store the ring as ONE flat **int32**
array (pixel bytes packed 4-per-element, little-endian — round-trips
``np.uint8.view(int32)`` ↔ ``lax.bitcast_convert_type``, verified on TPU
and CPU) whose rows are padded to a multiple of the 1024-element 1-D
tile, so every window's element range ``[idx·rowp, idx·rowp + w·rowp)``
is provably tile-aligned and a plain async DMA copies exactly the wanted
bytes. int32 rather than uint8 because Mosaic's scalar index arithmetic
is 32-bit: at the 1M-frame × 8192 B flagship shape BYTE offsets pass
2³¹ and a u8-element ring overflows into wild DMAs (measured
FAILED_PRECONDITION faults; an int32 ring's ELEMENT offsets stay < 2³¹
— asserted at construction). Measured: 16384 8-row windows from the 1M
ring in **3.7 ms (290 GB/s useful)** vs 44 ms for the tiled-gather pair
it replaces; correctness verified against high ring addresses.

Two kernels, both pipelined over ``NBUF`` DMA semaphores (the sweep
measured 1.2 µs/DMA at depth 8 — completion-latency-bound — down to
~0.2 µs at depth 64):

- ``gather_windows`` — HBM→HBM copy of ``n`` windows of ``w`` rows each
  (the fused sampler's obs+next-obs plane: one window covers both).
- ``scatter_rows``   — HBM→HBM copy of staged rows into the ring at
  arbitrary row indices (the flush path), ring aliased in place.

Rows never wrap inside a window: the ring carries ``w-1`` ghost rows per
sub-ring that mirror rows ``0..w-2`` (written twice by the flush), so
window starts are always contiguous (see replay/device_per.py).

Reference scope: the reference streams full pixel minibatches host→device
per step (SURVEY §3.1); this plane replaces that with device-resident
rows + on-device window composition, so only indices cross the host
boundary.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _side_effect_params():
    """``compiler_params`` marking the kernel side-effecting, in whichever
    spelling this jax takes: ``pltpu.CompilerParams(has_side_effects=...)``
    (new), ``TPUCompilerParams`` (mid), or the ``{"mosaic": {...}}`` dict
    (old, where the dataclass lacks the field)."""
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    try:
        return cls(has_side_effects=True)
    except TypeError:
        return dict(mosaic=dict(has_side_effects=True))

# 1-D int32 arrays tile at 1024 elements (4096 B) on TPU (Mosaic requires
# dynamic slice starts/sizes provably divisible by the tile) — all row
# strides here must be multiples of this.
I32_TILE = 1024
NBUF = 64  # outstanding DMAs (depth sweep: 8→1.2 µs/DMA, 64→~0.2 µs)


def padded_row_bytes(row_len: int) -> int:
    """Smallest tile-aligned row stride (BYTES) holding ``row_len`` pixel
    bytes; always a multiple of 4·I32_TILE."""
    return -(-row_len // (4 * I32_TILE)) * (4 * I32_TILE)


def _pipelined(n: int, dma):
    """Issue ``dma(k, slot)`` for k in [0, n), ``NBUF`` outstanding."""

    def body(sems):
        for k in range(min(NBUF, n)):
            dma(k, sems.at[k]).start()

        def loop(k, _):
            dma(k, sems.at[k % NBUF]).wait()

            @pl.when(k + NBUF < n)
            def _():
                dma(k + NBUF, sems.at[k % NBUF]).start()

            return 0

        lax.fori_loop(0, n, loop, 0)

    pl.run_scoped(body, pltpu.SemaphoreType.DMA((min(NBUF, n),)))


def _gather_kernel(n, wsz, rowb, idx_ref, ring_ref, out_ref):
    _pipelined(n, lambda k, sem: pltpu.make_async_copy(
        ring_ref.at[pl.ds(idx_ref[k] * rowb, wsz)],
        out_ref.at[pl.ds(k * wsz, wsz)], sem))


def _scatter_kernel(n, rowb, sidx_ref, didx_ref, staged_ref, ring_in_ref,
                    ring_out_ref):
    _pipelined(n, lambda k, sem: pltpu.make_async_copy(
        staged_ref.at[pl.ds(sidx_ref[k] * rowb, rowb)],
        ring_out_ref.at[pl.ds(didx_ref[k] * rowb, rowb)], sem))


def gather_windows(idx: jax.Array, ring: jax.Array, *, n: int, w: int,
                   rowb: int, interpret: bool = False) -> jax.Array:
    """Copy ``n`` contiguous ``w``-row windows out of the flat ring.

    ``idx`` [n] int32 — window-start ROW indices (callers guarantee
    ``idx + w`` stays inside the ring via ghost rows); ``ring`` [S] int32
    (packed pixel bytes); ``rowb`` row stride in BYTES. Returns
    [n · w · rowb/4] int32 (flat; reshape/bitcast at the consumer).
    """
    rowp = rowb // 4
    wsz = w * rowp
    kernel = functools.partial(_gather_kernel, n, wsz, rowp)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n * wsz,), jnp.int32),
        grid_spec=grid_spec,
        compiler_params=_side_effect_params(),
        interpret=interpret,
    )(idx.astype(jnp.int32), ring)


def scatter_rows(src_idx: jax.Array, dst_idx: jax.Array, staged: jax.Array,
                 ring: jax.Array, *, n: int, rowb: int,
                 interpret: bool = False) -> jax.Array:
    """Write ``n`` rows ``staged[src_idx[k]] → ring[dst_idx[k]]`` (row
    units; ``staged``/``ring`` flat int32, ``rowb`` in BYTES; the ring is
    aliased in place via input_output_aliases).

    ``src_idx`` decouples lane from source row so ghost rows re-send the
    same staged bytes to their mirror target without duplicating them
    host-side. There is no out-of-bounds drop — padding lanes must point
    at the ring's scratch row (the caller maps them), where racing
    same-destination DMAs are harmless. Distinct REAL targets within one
    call are the caller's invariant (one flush chunk never wraps a
    sub-ring; ghost copies target distinct rows by construction).
    """
    kernel = functools.partial(_scatter_kernel, n, rowb // 4)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(ring.shape, jnp.int32),
        grid_spec=grid_spec,
        input_output_aliases={3: 0},  # indexes include the scalar operands
        compiler_params=_side_effect_params(),
        interpret=interpret,
    )(src_idx.astype(jnp.int32), dst_idx.astype(jnp.int32), staged, ring)
