from distributed_deep_q_tpu.ops.losses import (  # noqa: F401
    huber,
    bellman_targets,
    dqn_loss,
    sequence_dqn_loss,
)
