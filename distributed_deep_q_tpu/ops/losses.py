"""DQN loss construction — the compute core the reference's ``Solver`` owns.

The reference Solver "builds Bellman targets (r + γ·max_a' Q_target(s',a')),
computes loss, runs fwd/bwd, extracts grads" (SURVEY.md §2 "Solver" [M][R]).
Here targets/loss are pure jax functions, differentiated by
``jax.value_and_grad`` inside the jitted train step, so forward+backward+
optimizer compile into a single XLA program (no per-minibatch Python↔C++
boundary like the reference's pycaffe hot loop, SURVEY §3.1).

All functions are shape-static and elementwise-fusable; XLA folds them into
the matmul epilogues on TPU. The optional Pallas fused variant lives in
``ops/pallas_kernels.py``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def huber(x: jax.Array, delta: float = 1.0) -> jax.Array:
    """Huber loss elementwise: quadratic within ±delta, linear outside."""
    abs_x = jnp.abs(x)
    quad = jnp.minimum(abs_x, delta)
    return 0.5 * quad * quad + delta * (abs_x - quad)


def bellman_targets(
    reward: jax.Array,           # [B] float32 (n-step summed on host)
    discount: jax.Array,         # [B] float32: γ^n · (1 - done)
    q_next_target: jax.Array,    # [B, A] target-net Q(s')
    q_next_online: jax.Array | None = None,  # [B, A] online Q(s') for DDQN
    double: bool = False,
) -> jax.Array:
    """r + γⁿ·(1-done)·Q⁻(s', a*) with a* from online net when ``double``.

    Double-DQN (van Hasselt 2016) decouples action selection (online net)
    from evaluation (target net); vanilla DQN maxes the target net directly.
    """
    if double:
        assert q_next_online is not None
        a_star = jnp.argmax(q_next_online, axis=-1)
        q_sel = jnp.take_along_axis(
            q_next_target, a_star[:, None], axis=-1)[:, 0]
    else:
        q_sel = jnp.max(q_next_target, axis=-1)
    return reward + discount * q_sel


def dqn_loss(
    q: jax.Array,         # [B, A] online Q(s)
    actions: jax.Array,   # [B] int32
    targets: jax.Array,   # [B] float32 (stop-gradient applied here)
    weights: jax.Array,   # [B] importance weights (ones when uniform)
    delta: float = 1.0,
) -> tuple[jax.Array, jax.Array]:
    """Weighted Huber TD loss. Returns (scalar loss, |TD| for PER updates)."""
    q_sa = jnp.take_along_axis(q, actions[:, None].astype(jnp.int32),
                               axis=-1)[:, 0]
    td = q_sa - jax.lax.stop_gradient(targets)
    loss = jnp.mean(weights * huber(td, delta))
    return loss, jnp.abs(jax.lax.stop_gradient(td))


def value_rescale(x: jax.Array, eps: float = 1e-3) -> jax.Array:
    """R2D2 invertible value rescaling h(x) = sign(x)(√(|x|+1)−1) + εx
    (Kapturowski et al. 2019, from Pohlen et al. 2018) — lets the recurrent
    learner train on unclipped rewards with bounded targets."""
    return jnp.sign(x) * (jnp.sqrt(jnp.abs(x) + 1.0) - 1.0) + eps * x


def value_rescale_inv(x: jax.Array, eps: float = 1e-3) -> jax.Array:
    """Analytic inverse of ``value_rescale``."""
    return jnp.sign(x) * (
        jnp.square((jnp.sqrt(1.0 + 4.0 * eps * (jnp.abs(x) + 1.0 + eps))
                    - 1.0) / (2.0 * eps)) - 1.0)


def sequence_bellman_targets(
    reward: jax.Array,          # [B, T]
    discount: jax.Array,        # [B, T]: γ·(1-done) per step
    q_next_target: jax.Array,   # [B, T, A] target net Q(s_{t+1})
    q_next_online: jax.Array | None = None,  # [B, T, A] for Double-DQN
    double: bool = True,
    rescale: bool = True,
) -> jax.Array:
    """Per-step targets h(r + γ·h⁻¹(Q⁻(s', a*))) over a sequence window."""
    if double:
        assert q_next_online is not None
        a_star = jnp.argmax(q_next_online, axis=-1)
    else:
        a_star = jnp.argmax(q_next_target, axis=-1)
    q_sel = jnp.take_along_axis(q_next_target, a_star[..., None],
                                axis=-1)[..., 0]
    if rescale:
        return value_rescale(reward + discount * value_rescale_inv(q_sel))
    return reward + discount * q_sel


def sequence_dqn_loss(
    q: jax.Array,         # [B, T, A] online Q over the training window
    actions: jax.Array,   # [B, T] int32
    targets: jax.Array,   # [B, T] float32
    mask: jax.Array,      # [B, T] 1.0 on valid steps, 0.0 past episode end
    weights: jax.Array,   # [B] per-sequence importance weights
    delta: float = 1.0,
    eta: float = 0.9,
) -> tuple[jax.Array, jax.Array]:
    """R2D2 sequence TD loss with validity masking.

    Returns (scalar loss, per-sequence priority) where priority follows the
    R2D2 mixed max/mean rule: η·max_t|TD| + (1-η)·mean_t|TD| (Kapturowski
    et al. 2019), computed over valid steps only.
    """
    q_sa = jnp.take_along_axis(q, actions[..., None].astype(jnp.int32),
                               axis=-1)[..., 0]
    td = (q_sa - jax.lax.stop_gradient(targets)) * mask
    per_t = huber(td, delta) * mask
    denom = jnp.maximum(jnp.sum(mask, axis=1), 1.0)
    per_seq = jnp.sum(per_t, axis=1) / denom
    loss = jnp.mean(weights * per_seq)

    abs_td = jnp.abs(jax.lax.stop_gradient(td))
    max_td = jnp.max(abs_td, axis=1)
    mean_td = jnp.sum(abs_td, axis=1) / denom
    priority = eta * max_td + (1.0 - eta) * mean_td
    return loss, priority
