"""JAX ports of the synthetic pixel envs — the Anakin acting substrate.

``actors/game.py``'s ``SignalAtari`` / ``VelocitySignalAtari`` step
functions re-expressed in ``jax.numpy`` so acting can live INSIDE the
jitted program (Podracer's Anakin endpoint, PAPERS.md arXiv:2104.06272):
vmapped over N envs, scanned over T ticks, zero host round-trips.

Semantics match the numpy envs op-for-op — background 20 / band 220,
reward keyed on the PRE-step target, the returned frame rendered from
the POST-step target, auto-reset folded into ``step`` (the numpy fleet's
caller does step-then-reset; here ``done`` selects the reset branch in
the same tick). The RNG is the one deliberate difference: numpy's Philox
``Generator`` cannot be reproduced bitwise with ``jax.random``, so each
env carries its own JAX key and the port defines its OWN deterministic
stream — the Anakin-vs-host-loop pin compares two drivers of THESE envs,
not numpy vs jax.

Every env is a ``(reset_fn, step_fn)`` pair over a dict-of-arrays state:
``reset_fn(key) -> (state, frame)``,
``step_fn(state, action) -> (state, frame, reward, done)``
with u8 frames, f32 rewards, bool dones — vmap over a leading key/state
axis for the stacked fleet.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _band_frame(frame_shape, orientation: str, lo, width):
    """[H, W] u8 frame: background 20, one band of 220 covering axis
    positions ``[lo, lo+width)`` (mod axis length) — vertical bands are
    column ranges, horizontal are row ranges."""
    h, w = frame_shape
    axis = w if orientation == "v" else h
    pos = jnp.arange(axis, dtype=jnp.int32)
    mask = ((pos - lo) % axis) < width
    frame = jnp.where(mask, jnp.uint8(220), jnp.uint8(20))
    if orientation == "v":
        return jnp.broadcast_to(frame[None, :], (h, w))
    return jnp.broadcast_to(frame[:, None], (h, w))


def make_signal_env(frame_shape=(84, 84), num_actions: int = 4,
                    episode_len: int = 32, orientation: str = "v"):
    """JAX ``SignalAtari``: static band at ``target * band_width``; the
    target redraws EVERY step, so reward demands reading the current
    frame (no constant policy scores above chance)."""
    h, w = frame_shape
    axis = w if orientation == "v" else h
    band = max(axis // num_actions, 1)

    def render(target):
        return _band_frame(frame_shape, orientation, target * band, band)

    def reset_fn(key):
        key, kt = jax.random.split(key)
        target = jax.random.randint(kt, (), 0, num_actions, jnp.int32)
        state = {"t": jnp.int32(0), "target": target, "key": key}
        return state, render(target)

    def step_fn(state, action):
        key, k_step, k_reset = jax.random.split(state["key"], 3)
        reward = (action == state["target"]).astype(jnp.float32)
        t = state["t"] + 1
        done = t >= episode_len
        # the numpy caller does step (one draw) then, on done, reset
        # (another draw); both draws happen here and done selects
        target = jnp.where(
            done,
            jax.random.randint(k_reset, (), 0, num_actions, jnp.int32),
            jax.random.randint(k_step, (), 0, num_actions, jnp.int32))
        t = jnp.where(done, jnp.int32(0), t)
        state = {"t": t, "target": target, "key": key}
        return state, render(target), reward, done

    return reset_fn, step_fn


def make_velocity_signal_env(frame_shape=(84, 84), num_actions: int = 4,
                             episode_len: int = 32,
                             orientation: str = "v", segment: int = 8):
    """JAX ``VelocitySignalAtari``: a band MOVES at one of ``num_actions``
    signed velocities; the velocity index is the correct action, so the
    policy must integrate ≥2 frames. ``segment=0`` holds the velocity
    for the whole episode (the memory-gate tier)."""
    h, w = frame_shape
    axis = w if orientation == "v" else h
    seg = int(segment) if segment else episode_len + 1
    band_width = max(3, axis // 8)
    unit = max(2, axis // 16)
    half = num_actions // 2
    units = list(range(-half, 0)) + list(range(1, num_actions - half + 1))
    velocities = jnp.asarray([unit * m for m in units], jnp.int32)

    def render(pos):
        return _band_frame(frame_shape, orientation, pos, band_width)

    def _redraw(kv, kp):
        # numpy order: velocity index first, then position
        return (jax.random.randint(kv, (), 0, num_actions, jnp.int32),
                jax.random.randint(kp, (), 0, axis, jnp.int32))

    def reset_fn(key):
        key, kv, kp = jax.random.split(key, 3)
        v_idx, pos = _redraw(kv, kp)
        state = {"t": jnp.int32(0), "v_idx": v_idx, "pos": pos, "key": key}
        return state, render(pos)

    def step_fn(state, action):
        key, kv1, kp1, kv2, kp2 = jax.random.split(state["key"], 5)
        reward = (action == state["v_idx"]).astype(jnp.float32)
        t = state["t"] + 1
        redraw = (t % seg) == 0
        v_draw, p_draw = _redraw(kv1, kp1)
        advanced = (state["pos"] + velocities[state["v_idx"]]) % axis
        v_idx = jnp.where(redraw, v_draw, state["v_idx"])
        pos = jnp.where(redraw, p_draw, advanced)
        done = t >= episode_len
        v_reset, p_reset = _redraw(kv2, kp2)
        v_idx = jnp.where(done, v_reset, v_idx)
        pos = jnp.where(done, p_reset, pos)
        t = jnp.where(done, jnp.int32(0), t)
        state = {"t": t, "v_idx": v_idx, "pos": pos, "key": key}
        return state, render(pos), reward, done

    return reset_fn, step_fn


def make_jax_env(cfg):
    """``make_env``'s dispatch for the JAX-expressible kinds.

    ``cfg`` is an ``EnvConfig`` with ``kind == "signal_atari"``; id
    suffixes select exactly as the numpy dispatcher does ("-h"
    horizontal, "-vel" velocity, "-ep" whole-episode velocity hold).
    """
    if cfg.kind != "signal_atari":
        raise ValueError(
            f"no JAX port for env kind {cfg.kind!r} — Anakin covers the "
            "signal_atari family; other envs act through the vectorized "
            "or per-env host loops")
    orientation = "h" if cfg.id.endswith("-h") else "v"
    if "-vel" in cfg.id:
        return make_velocity_signal_env(
            frame_shape=tuple(cfg.frame_shape), orientation=orientation,
            segment=0 if "-ep" in cfg.id else 8)
    return make_signal_env(frame_shape=tuple(cfg.frame_shape),
                           orientation=orientation)
