"""Health plane (ISSUE 13): windowed series, SLO burn rates, one verdict.

PRs 1 and 7 built the EMIT side of observability — histograms, gauges,
causal spans — but nothing consumed those signals live. This module is
the read side: a fixed-capacity time-series ring over sampled gauges
and histogram *deltas* (``Histogram.snapshot``/``delta`` turn the
cumulative telemetry histograms into sliding windows), declarative SLO
rules with multi-window burn-rate alerting (the Google SRE workbook
shape: a rule FIRES only when both a fast and a slow window have burned
through their error budget, which kills single-spike flaps; it CLEARS
with hysteresis when the fast window cools below ``clear_ratio``),
plus trend detectors (monotonic queue growth, p99 drift, ingest-rate
collapse) that need no target at all. Everything reduces to one
structured ``HealthVerdict {ok|degraded|critical, findings[]}`` — the
machine-readable signal ROADMAP item 5's autoscaler will consume.

Deployment shape: each server (replay feed, inference) owns a
``HealthMonitor`` sampling its own telemetry and answers a ``health``
RPC verb with its verdict; the supervisor's ``FleetHealth`` scrapes
every member into ONE fleet verdict surfaced in the run JSONL
(``health/verdict``) and ``scripts/telemetry_report.py``.

Cost discipline mirrors ``tracing.py``: a module ``ENABLED`` flag is
the single branch on every entry point, and the disabled path returns
preallocated singletons (``NULL_VERDICT``, ``_EMPTY_GAUGES``) without
allocating — pinned by ``tests/test_health.py``.
"""

from __future__ import annotations

import fnmatch
import json
import math
import threading
import time
from dataclasses import dataclass, field

__all__ = [
    "ENABLED", "configure", "configure_from", "disable", "reset",
    "SLORule", "TrendRule", "HealthFinding", "HealthVerdict",
    "NULL_VERDICT", "SeriesRing", "HealthMonitor", "FleetHealth",
    "verdict_to_wire", "verdict_from_wire",
    "default_server_rules", "default_server_trends",
    "default_inference_rules", "default_inference_trends",
    "default_learn_rules", "default_learn_trends",
]

ENABLED = False  # module flag: the single branch on every hot path

# module defaults — per-rule overrides win when set (tests and the
# chaos gate shrink the windows to seconds; production keeps minutes)
_RING_CAP = 512
_FAST_WINDOW_S = 30.0
_SLOW_WINDOW_S = 300.0
_CLEAR_RATIO = 0.5

_SEVERITIES = ("ok", "degraded", "critical")  # worst-of ordering


def configure(enabled: bool = False, ring_capacity: int = 512,
              fast_window_s: float = 30.0, slow_window_s: float = 300.0,
              clear_ratio: float = 0.5) -> None:
    """Set module state from config values (``cfg.health``). Monitors
    created earlier keep their ring capacity (configure first)."""
    global ENABLED, _RING_CAP, _FAST_WINDOW_S, _SLOW_WINDOW_S
    global _CLEAR_RATIO
    _RING_CAP = max(int(ring_capacity), 8)
    _FAST_WINDOW_S = max(float(fast_window_s), 1e-3)
    _SLOW_WINDOW_S = max(float(slow_window_s), _FAST_WINDOW_S)
    _CLEAR_RATIO = min(max(float(clear_ratio), 0.0), 1.0)
    ENABLED = bool(enabled)


def configure_from(health_cfg) -> None:
    """``configure`` from a ``config.HealthConfig`` instance."""
    configure(enabled=health_cfg.enabled,
              ring_capacity=health_cfg.ring_capacity,
              fast_window_s=health_cfg.fast_window_s,
              slow_window_s=health_cfg.slow_window_s,
              clear_ratio=health_cfg.clear_ratio)


def disable() -> None:
    global ENABLED
    ENABLED = False


def reset() -> None:
    """Test hook: restore module defaults (monitors are per-instance
    state and are simply dropped by their owners)."""
    configure()


# -- declarative rules ------------------------------------------------------
@dataclass(frozen=True)
class SLORule:
    """Target + multi-window burn-rate alert over one metric key.

    ``key`` is an ``fnmatch`` pattern over sampled series names (e.g.
    ``rpc/*_ms_p99``). ``mode``: ``above`` — a sample violates when
    value > target; ``below`` — when value < target; ``rate_above`` —
    the per-second delta between consecutive samples violates when it
    exceeds target (the shape for cumulative counters: target 0.0 means
    "this counter must not move", e.g. ``rpc/checksum_errors``).
    ``budget`` is the violating-sample fraction the SLO tolerates; the
    burn rate of a window is violating-fraction / budget, and the rule
    fires when BOTH windows burn ≥ 1.
    """

    name: str
    key: str
    target: float
    mode: str = "above"          # above | below | rate_above
    budget: float = 0.1
    severity: str = "degraded"   # degraded | critical
    fast_window_s: float | None = None  # None → module default
    slow_window_s: float | None = None
    clear_ratio: float | None = None

    def __post_init__(self):
        if self.mode not in ("above", "below", "rate_above"):
            raise ValueError(f"unknown SLO mode {self.mode!r}")
        if self.severity not in ("degraded", "critical"):
            raise ValueError(f"unknown severity {self.severity!r}")


@dataclass(frozen=True)
class TrendRule:
    """Targetless shape detector over one series.

    ``kind``: ``monotonic_growth`` — the window never decreases and
    grows by ≥ ``ratio``× overall (queue that only fills is a leak even
    before any absolute threshold trips); ``drift`` — the latest sample
    exceeds ``ratio``× the window median AND sits above ``floor``
    (p99 creep; the floor keeps the multiplicative noise of a fast
    series — a windowed p99 jumping 0.2→1 ms between scrapes — from
    reading as drift); ``collapse`` — the latest sample falls below
    ``ratio``× the window median while the median itself sat above
    ``floor`` (an ingest rate that was genuinely flowing and then
    died — there the floor keeps an idle series from "collapsing"
    from zero to zero).
    """

    name: str
    key: str
    kind: str                    # monotonic_growth | drift | collapse
    ratio: float = 2.0
    min_points: int = 4
    floor: float = 0.0
    severity: str = "degraded"

    def __post_init__(self):
        if self.kind not in ("monotonic_growth", "drift", "collapse"):
            raise ValueError(f"unknown trend kind {self.kind!r}")


@dataclass(frozen=True)
class HealthFinding:
    """One violated rule, with enough numbers to act on it."""

    rule: str
    key: str
    severity: str = "degraded"
    kind: str = "slo"            # slo | trend | fleet
    value: float = float("nan")
    target: float = float("nan")
    burn_fast: float = 0.0
    burn_slow: float = 0.0
    member: str = ""             # set by FleetHealth aggregation
    detail: str = ""

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "key": self.key,
             "severity": self.severity, "kind": self.kind,
             "value": None if math.isnan(self.value) else self.value,
             "target": None if math.isnan(self.target) else self.target,
             "burn_fast": round(self.burn_fast, 4),
             "burn_slow": round(self.burn_slow, 4)}
        if self.member:
            d["member"] = self.member
        if self.detail:
            d["detail"] = self.detail
        return d

    @staticmethod
    def from_dict(d: dict) -> "HealthFinding":
        v, t = d.get("value"), d.get("target")
        return HealthFinding(
            rule=d.get("rule", ""), key=d.get("key", ""),
            severity=d.get("severity", "degraded"),
            kind=d.get("kind", "slo"),
            value=float("nan") if v is None else float(v),
            target=float("nan") if t is None else float(t),
            burn_fast=float(d.get("burn_fast", 0.0)),
            burn_slow=float(d.get("burn_slow", 0.0)),
            member=d.get("member", ""), detail=d.get("detail", ""))


@dataclass(frozen=True)
class HealthVerdict:
    """The one ops answer: status + the findings that justify it."""

    status: str = "ok"           # ok | degraded | critical
    findings: tuple = ()
    t: float = 0.0

    @property
    def ok(self) -> bool:
        return self.status == "ok"

    def to_jsonable(self) -> dict:
        return {"status": self.status, "ok": self.ok,
                "t": round(self.t, 3),
                "findings": [f.to_dict() for f in self.findings]}


# preallocated disabled-path singletons (zero-cost pin in test_health)
NULL_VERDICT = HealthVerdict()
_EMPTY_GAUGES: dict = {}


def _worse(a: str, b: str) -> str:
    return a if _SEVERITIES.index(a) >= _SEVERITIES.index(b) else b


# -- wire helpers -----------------------------------------------------------
# rpc/protocol.py frames are FLAT dicts (scalars/strings/arrays only),
# so findings cross the wire as one JSON string — no version bump.
def verdict_to_wire(v: HealthVerdict) -> dict:
    return {"status": v.status, "ok": v.ok,
            "n_findings": len(v.findings), "t": float(v.t),
            "findings_json": json.dumps([f.to_dict()
                                         for f in v.findings])}


def verdict_from_wire(reply: dict) -> HealthVerdict:
    findings = tuple(HealthFinding.from_dict(d) for d in
                     json.loads(reply.get("findings_json", "[]")))
    return HealthVerdict(status=str(reply.get("status", "ok")),
                         findings=findings,
                         t=float(reply.get("t", 0.0)))


# -- fixed-capacity time series --------------------------------------------
class SeriesRing:
    """Drop-oldest ring of (t, value) samples — O(1) push, bounded
    memory regardless of run length (same discipline as tracing's span
    ring)."""

    __slots__ = ("cap", "_t", "_v", "n")

    def __init__(self, cap: int):
        self.cap = max(int(cap), 1)
        self._t = [0.0] * self.cap
        self._v = [0.0] * self.cap
        self.n = 0

    def push(self, t: float, v: float) -> None:
        i = self.n % self.cap
        self._t[i] = t
        self._v[i] = v
        self.n += 1

    def __len__(self) -> int:
        return min(self.n, self.cap)

    def items(self) -> list[tuple[float, float]]:
        """Oldest-first (t, v) pairs currently held."""
        if self.n <= self.cap:
            return list(zip(self._t[:self.n], self._v[:self.n]))
        i = self.n % self.cap
        return (list(zip(self._t[i:], self._v[i:]))
                + list(zip(self._t[:i], self._v[:i])))

    def last(self) -> tuple[float, float] | None:
        if self.n == 0:
            return None
        i = (self.n - 1) % self.cap
        return (self._t[i], self._v[i])


# -- rule evaluation (pure functions over window slices) --------------------
def _window(items: list, now: float, span: float) -> list:
    return [(t, v) for t, v in items if now - t <= span]


def _burn(items: list, rule: SLORule, now: float, span: float) -> float:
    """Burn rate of one window: violating-sample fraction / budget."""
    w = _window(items, now, span)
    if rule.mode == "rate_above":
        if len(w) < 2:
            return 0.0
        viol = n = 0
        for (t0, v0), (t1, v1) in zip(w, w[1:]):
            n += 1
            if (v1 - v0) / max(t1 - t0, 1e-9) > rule.target:
                viol += 1
        frac = viol / n
    else:
        if not w:
            return 0.0
        if rule.mode == "above":
            viol = sum(1 for _, v in w if v > rule.target)
        else:
            viol = sum(1 for _, v in w if v < rule.target)
        frac = viol / len(w)
    return frac / max(rule.budget, 1e-9)


def _median(vals: list[float]) -> float:
    s = sorted(vals)
    m = len(s) // 2
    return s[m] if len(s) % 2 else 0.5 * (s[m - 1] + s[m])


def _trend_hit(items: list, rule: TrendRule, now: float,
               span: float) -> tuple[bool, float, float]:
    """-> (fired, latest_value, reference_value)."""
    w = _window(items, now, span)
    if len(w) < rule.min_points:
        return (False, float("nan"), float("nan"))
    vals = [v for _, v in w]
    last = vals[-1]
    if rule.kind == "monotonic_growth":
        mono = all(b >= a for a, b in zip(vals, vals[1:]))
        base = vals[0]
        grew = last >= rule.ratio * base if base > 0 else last > 0
        return (mono and last > base and grew, last, base)
    med = _median(vals[:-1])
    if rule.kind == "drift":
        # the floor gates materiality: windowed p99s of a fast series
        # fluctuate multiplicatively (a scrape window holds only a
        # handful of samples), so a ratio alone fires on quantization
        # noise — creep counts once the level itself matters
        return (med > 0 and last > rule.floor
                and last > rule.ratio * med, last, med)
    # collapse: was genuinely flowing (median above floor), now dead
    return (med > rule.floor and last < rule.ratio * med, last, med)


# -- per-process monitor ----------------------------------------------------
class HealthMonitor:
    """Samples watched gauges / histogram deltas into rings and reduces
    the declarative rules to one local ``HealthVerdict``.

    One lock guards all mutable structures — ``sample`` runs on the
    owner's telemetry cadence while ``verdict`` answers the ``health``
    RPC from serve threads. Only keys matched by some rule/trend
    pattern are stored (bounded memory, no per-sample allocation for
    unwatched keys); when the module is disabled every entry point is
    a single flag branch returning preallocated constants.
    """

    def __init__(self, rules: tuple = (), trends: tuple = (),
                 name: str = "", ring_capacity: int | None = None):
        # RLock: the _watched/_push helpers re-acquire lexically under
        # callers that already hold it (lock-discipline pass idiom)
        self._lock = threading.RLock()
        self.name = name
        self.rules = tuple(rules)
        self.trends = tuple(trends)
        self._cap = int(ring_capacity) if ring_capacity else _RING_CAP
        self._patterns = tuple(sorted({r.key for r in self.rules}
                                      | {t.key for t in self.trends}))
        self._watch_cache: dict[str, bool] = {}
        self._series: dict[str, SeriesRing] = {}
        self._rule_state: dict[tuple, bool] = {}  # (rule, key) -> active
        self._prev_snaps: dict = {}
        self._n_samples = 0
        self._last_verdict = NULL_VERDICT

    def _watched(self, key: str) -> bool:
        with self._lock:
            hit = self._watch_cache.get(key)
            if hit is None:
                hit = any(fnmatch.fnmatchcase(key, p)
                          for p in self._patterns)
                self._watch_cache[key] = hit
            return hit

    def sample(self, gauges: dict | None = None,
               hists: dict | None = None,
               t: float | None = None) -> None:
        """Record one sampling tick. ``gauges`` is a flat name→scalar
        dict (e.g. ``telemetry_summary()``); ``hists`` maps series
        prefix → cumulative ``Histogram`` *snapshot* — each is diffed
        against the previous snapshot and the window's p99 lands in the
        ``{prefix}_p99`` series, OVERWRITING any cumulative gauge of
        the same name sampled this tick (windowed beats
        since-process-start for alerting)."""
        if not ENABLED:
            return
        if t is None:
            t = time.monotonic()
        with self._lock:
            # keys a histogram feeds are OWNED by the windowed path:
            # pushing the cumulative gauge too would pin the ring at the
            # since-start p99 (one bad era then violates forever), and
            # a quiet window must age out to nothing — not fall back to
            # the cumulative value — for its rule to clear
            owned = {name + "_p99" for name in hists} if hists else set()
            if gauges:
                for k, v in gauges.items():
                    if k in owned:
                        continue
                    if isinstance(v, (int, float)) and self._watched(k):
                        self._push(k, t, float(v))
            if hists:
                for name, snap in hists.items():
                    prev = self._prev_snaps.get(name)
                    self._prev_snaps[name] = snap
                    key = name + "_p99"
                    if not self._watched(key):
                        continue
                    win = snap.delta(prev) if prev is not None else snap
                    if win.count:
                        self._push(key, t, win.percentile(0.99))
            self._n_samples += 1

    def _push(self, key: str, t: float, v: float) -> None:
        with self._lock:
            ring = self._series.get(key)
            if ring is None:
                ring = self._series[key] = SeriesRing(self._cap)
            ring.push(t, v)

    def verdict(self, t: float | None = None) -> HealthVerdict:
        """Evaluate every rule against the current rings. SLO rules
        are stateful: fire when both windows burn ≥ 1, stay active
        until the fast window cools below ``clear_ratio`` (hysteresis —
        a rule flapping around burn=1 does not flap the verdict)."""
        if not ENABLED:
            return NULL_VERDICT
        if t is None:
            t = time.monotonic()
        findings: list[HealthFinding] = []
        with self._lock:
            keys = list(self._series)
            for rule in self.rules:
                fast = rule.fast_window_s or _FAST_WINDOW_S
                slow = rule.slow_window_s or _SLOW_WINDOW_S
                clear = (rule.clear_ratio if rule.clear_ratio
                         is not None else _CLEAR_RATIO)
                for key in keys:
                    if not fnmatch.fnmatchcase(key, rule.key):
                        continue
                    items = self._series[key].items()
                    bf = _burn(items, rule, t, fast)
                    bs = _burn(items, rule, t, slow)
                    sid = (rule.name, key)
                    active = self._rule_state.get(sid, False)
                    if active:
                        active = bf >= clear
                    else:
                        active = bf >= 1.0 and bs >= 1.0
                    self._rule_state[sid] = active
                    if active:
                        last = self._series[key].last()
                        findings.append(HealthFinding(
                            rule=rule.name, key=key,
                            severity=rule.severity, kind="slo",
                            value=last[1] if last else float("nan"),
                            target=rule.target,
                            burn_fast=bf, burn_slow=bs))
            for trend in self.trends:
                slow = _SLOW_WINDOW_S
                for key in keys:
                    if not fnmatch.fnmatchcase(key, trend.key):
                        continue
                    hit, last, ref = _trend_hit(
                        self._series[key].items(), trend, t, slow)
                    if hit:
                        findings.append(HealthFinding(
                            rule=trend.name, key=key,
                            severity=trend.severity, kind="trend",
                            value=last, target=ref,
                            detail=trend.kind))
            status = "ok"
            for f in findings:
                status = _worse(status, f.severity)
            v = HealthVerdict(status, tuple(findings), t)
            self._last_verdict = v
            return v

    def gauges(self) -> dict[str, float]:
        """Monitor self-accounting for the metrics spine."""
        if not ENABLED:
            return _EMPTY_GAUGES
        with self._lock:
            v = self._last_verdict
            return {"health/samples": float(self._n_samples),
                    "health/series": float(len(self._series)),
                    "health/findings": float(len(v.findings)),
                    "health/degraded": float(v.status == "degraded"),
                    "health/critical": float(v.status == "critical")}

    def scrape(self, gauges: dict | None = None,
               hists: dict | None = None,
               t: float | None = None) -> dict:
        """sample + verdict + wire encode in one call — the body of the
        servers' ``health`` RPC verb."""
        if not ENABLED:
            return verdict_to_wire(NULL_VERDICT)
        self.sample(gauges, hists, t)
        return verdict_to_wire(self.verdict(t))


# -- fleet aggregation ------------------------------------------------------
class FleetHealth:
    """Supervisor-side aggregator: scrapes every registered member's
    ``health`` endpoint (an in-process callable or an RPC client bound
    method, both returning the flat wire dict) into ONE fleet verdict —
    worst-of member statuses, findings tagged with their member, and an
    unreachable member itself a degraded finding (a health plane that
    goes silent is not healthy)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._members: dict[str, object] = {}
        self._fleet_verdict = NULL_VERDICT
        self._scrape_errors = 0

    def register(self, name: str, scrape_fn) -> None:
        with self._lock:
            self._members[name] = scrape_fn

    def deregister(self, name: str) -> bool:
        """Drop a member from the scrape set — the elastic-fleet leave
        path (actors/membership.py): a host that handed its shard off
        and left ON PURPOSE must stop burning the unreachable budget.
        Returns False if the name was never registered."""
        with self._lock:
            return self._members.pop(name, None) is not None

    def scrape(self, t: float | None = None) -> HealthVerdict:
        if not ENABLED:
            return NULL_VERDICT
        if t is None:
            t = time.monotonic()
        with self._lock:
            members = list(self._members.items())
        findings: list[HealthFinding] = []
        status = "ok"
        for name, fn in members:
            try:
                wire = fn()
                mv = verdict_from_wire(wire)
            except Exception as e:  # noqa: BLE001 — member down IS the signal
                with self._lock:
                    self._scrape_errors += 1
                findings.append(HealthFinding(
                    rule="member_unreachable", key=name,
                    severity="degraded", kind="fleet", member=name,
                    detail=f"{type(e).__name__}: {e}"))
                status = _worse(status, "degraded")
                continue
            status = _worse(status, mv.status)
            for f in mv.findings:
                findings.append(HealthFinding(
                    rule=f.rule, key=f.key, severity=f.severity,
                    kind=f.kind, value=f.value, target=f.target,
                    burn_fast=f.burn_fast, burn_slow=f.burn_slow,
                    member=name, detail=f.detail))
        v = HealthVerdict(status, tuple(findings), t)
        with self._lock:
            self._fleet_verdict = v
        return v

    def last(self) -> HealthVerdict:
        with self._lock:
            return self._fleet_verdict

    def gauges(self) -> dict[str, float]:
        if not ENABLED:
            return _EMPTY_GAUGES
        with self._lock:
            v = self._fleet_verdict
            return {"health/members": float(len(self._members)),
                    "health/scrape_errors": float(self._scrape_errors),
                    "health/findings": float(len(v.findings)),
                    "health/degraded": float(v.status == "degraded"),
                    "health/critical": float(v.status == "critical")}


# -- default rule sets ------------------------------------------------------
def default_server_rules() -> tuple:
    """Replay feed server SLOs. ``wire_integrity`` is the chaos gate's
    trigger: a CRC-rejected frame rate above zero burns the budget
    deterministically under an injected corrupt fault."""
    return (
        SLORule(name="wire_integrity", key="rpc/checksum_errors",
                target=0.0, mode="rate_above", budget=0.05),
        SLORule(name="flush_p99", key="rpc/add_transitions_ms_p99",
                target=250.0, mode="above", budget=0.25),
        SLORule(name="credit_starvation", key="flow/credit_starvation",
                target=0.5, mode="above", budget=0.5),
        SLORule(name="ingest_shed", key="rpc/shed_flushes",
                target=0.0, mode="rate_above", budget=0.5),
    )


def default_server_trends() -> tuple:
    return (
        TrendRule(name="staged_growth", key="queue/staged_rows",
                  kind="monotonic_growth", ratio=2.0, min_points=6),
        TrendRule(name="ingest_collapse", key="flow/ingest_rate",
                  kind="collapse", ratio=0.2, floor=1.0),
        # floor: a tenth of the tightest latency SLO on these keys —
        # sub-floor windowed p99s are sample-count quantization, not
        # creep, and would otherwise flap the fleet verdict under
        # perfectly healthy sub-millisecond traffic
        TrendRule(name="rpc_p99_drift", key="rpc/*_ms_p99",
                  kind="drift", ratio=3.0, min_points=6, floor=25.0),
    )


def default_inference_rules() -> tuple:
    return (
        SLORule(name="infer_latency", key="inference/latency_ms_p99",
                target=50.0, mode="above", budget=0.25),
        SLORule(name="infer_shed", key="inference/sheds",
                target=0.0, mode="rate_above", budget=0.5),
    )


def default_tenant_rules() -> tuple:
    """Per-tenant serving SLOs (ISSUE 20). Keys are fnmatch patterns
    over the dynamic ``tenant/<tag>/*`` gauge surface, so a finding
    NAMES the tenant that burned its budget via the matched key — the
    chaos gate asserts the verdict JSONL carries those names."""
    return (
        SLORule(name="tenant_latency", key="tenant/*/latency_ms_p99",
                target=50.0, mode="above", budget=0.25),
        SLORule(name="tenant_shed", key="tenant/*/sheds",
                target=0.0, mode="rate_above", budget=0.5),
    )


def default_inference_trends() -> tuple:
    return (
        TrendRule(name="infer_queue_growth", key="inference/queued_rows",
                  kind="monotonic_growth", ratio=2.0, min_points=6),
    )


def default_learn_rules() -> tuple:
    """Learning-dynamics SLOs (ISSUE 16, ``learning.py``'s ``learn/*``
    gauges). A non-finite loss is the one hard divergence fact — any
    sustained rate of NaN/inf steps is critical; everything softer is a
    trend below."""
    return (
        SLORule(name="loss_nonfinite", key="learn/loss_nonfinite",
                target=0.0, mode="above", budget=0.25,
                severity="critical"),
    )


def default_learn_trends() -> tuple:
    """Targetless divergence detectors over the learner's own dynamics.
    ``loss_divergence`` is the chaos gate's named finding (an lr spike
    must walk the fleet verdict ok → degraded → ok —
    scripts/chaos_smoke.py divergence mode)."""
    return (
        TrendRule(name="loss_divergence", key="learn/loss",
                  kind="drift", ratio=5.0, min_points=4),
        TrendRule(name="loss_collapse", key="learn/loss",
                  kind="collapse", ratio=0.02, floor=1e-5),
        TrendRule(name="grad_norm_spike", key="learn/grad_norm",
                  kind="drift", ratio=10.0, min_points=4),
        TrendRule(name="q_overestimation", key="learn/q_max",
                  kind="monotonic_growth", ratio=3.0, min_points=6),
        TrendRule(name="priority_collapse", key="learn/prio_mean",
                  kind="collapse", ratio=0.05, floor=1e-5),
    )
