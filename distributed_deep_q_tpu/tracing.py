"""Sampling distributed tracer — causal spans across the data path.

The telemetry spine (PR 1) answers "how slow is the pipeline on
average"; this module answers "WHERE does a transition's time go":
actor env-step → flush (token-bucket wait, SHED/retry cycles) → server
recv/CRC/decode → ``replay_lock`` wait vs hold → ring insert → sample →
host→device transfer → fused-chain step, plus the durability plane's
snapshot/restore. Every hop records a span; causal context (trace id +
parent span id) crosses the RPC boundary as plain ``tr_*`` dict keys on
existing wire frames — the same piggyback the ``tm_*`` telemetry arrays
use, so NO wire version bump and v4 peers without context stay valid
(rpc/protocol.py documents the precedent).

Design constraints, in order:

1. **Near-zero cost when disabled.** ``ENABLED`` is a module-level bool;
   every entry point branches on it ONCE and returns a preallocated
   singleton (``span()`` → ``_NULL``, a no-op context manager) or an
   empty constant. No dict/list/closure allocation on the disabled path.
2. **Never block the data path.** Spans buffer in per-thread ring
   buffers: bounded, drop-OLDEST on overflow, drop counter exposed
   (``drop_count``/``counters``). A burst costs old spans, never memory
   or latency.
3. **Cross-process timestamps must be comparable.** Each process anchors
   ``time.perf_counter()`` to the wall clock once at import
   (``now() = t0_wall + (perf_counter() - t0_mono)``) so timestamps are
   monotonic *within* a process; the NTP-style ``estimate_skew`` (four
   stamps riding a request/reply pair) measures the remaining
   cross-process offset, which corrects lineage birth stamps before
   they are sent and shifts exported shards at merge time
   (``scripts/trace_report.py``).

**Sampling** is deterministic and counter-based (every k-th cycle, k
from ``sample_rate``) rather than RNG-based: no random() call on the
hot path and reproducible overhead. Span names are drawn from the
closed ``STAGES``/``EVENTS`` tables below — ``analysis/metric_keys.py``
statically rejects a span name that is not in them.

Pure stdlib (json/os/threading/time): importable by the analysis suite,
scripts, and actors without touching jax.
"""

from __future__ import annotations

import json
import os
import threading
import time

# -- the closed span-name tables (analysis/metric_keys.py enforces) --------
# Durations ("X" complete events). Server-side ``wire_recv`` covers the
# post-header payload read only — the blocking wait for a peer's next
# request is idle time, not pipeline work.
STAGES = (
    "env_step",        # one environment step on an actor
    "flush",           # whole add_transitions cycle incl. retries/sheds
    "bucket_wait",     # client token-bucket backpressure sleep
    "rpc_call",        # one wire round trip (send → reply decoded)
    "wire_recv",       # payload+trailer bytes off the socket
    "crc_verify",      # wire-v4 CRC-32C check
    "wire_decode",     # frame bytes → message dict
    "lock_wait",       # waiting to acquire a traced lock
    "lock_hold",       # critical section under a traced lock
    "ingest_parse",    # add_transitions payload parse/prep, OFF-lock
    "ring_insert",     # replay add_batch under replay_lock
    "staged_append",   # columnar stage memcpy (replay/columnar.py)
    "ingest_drain",    # batched staging→device flush (drain thread)
    "sample",          # replay sample (host compose / device draw)
    "stage_batch",     # DeviceStager cycle (sample + device_put)
    "device_put",      # host→device transfer of a sampled batch
    "train_step",      # train-step dispatch (fused chain or per-step)
    "param_pull",      # actor get_params round trip
    "infer_wait",      # inference serve thread waiting on its microbatch
    "infer_batch",     # microbatch cut: stack + pad to a compiled bucket
    "infer_forward",   # the ONE device-resident jit'd policy forward
    "infer_shadow",    # mirrored shadow-tenant forwards + drift diff
    "remote_infer",    # actor-side infer round trip (obs out, action back)
    "vector_step",     # one vectorized actor tick (N actions + batched step)
    "vector_infer",    # vector actor's batched infer round trip (one RPC)
    "anakin_superstep",  # fully-jitted act+insert+train dispatch (host side)
    "snapshot_capture",  # durability: state capture under locks
    "snapshot_write",  # durability: serialize + atomic write (off-lock)
    "restore",         # durability: warm-boot generation walk
)
# Points in time ("i" instant events).
EVENTS = (
    "shed",            # server shed this flush; client will re-send
    "retry",           # client retry after a transport error
    "reconnect",       # client re-established its connection
    "degraded",        # flow controller tripped degraded mode
)

_VALID_NAMES = frozenset(STAGES) | frozenset(EVENTS)

# wire piggyback keys (plain dict entries — no wire version bump; see
# rpc/protocol.py "evolution without a version bump")
KEY_TRACE = "tr_trace"      # int: trace id of the sender's current span
KEY_SPAN = "tr_span"        # int: sender's span id (the remote parent)
KEY_SENT_AT = "tr_sent_at"  # float: sender's anchored wall clock at send
KEY_RECV_AT = "tr_recv_at"  # float: server clock on request entry (t2)
KEY_DONE_AT = "tr_done_at"  # float: server clock on reply build (t3)
KEY_BIRTH = "tr_birth"      # float64[n]: per-row birth stamps (lineage)

ENABLED = False  # module flag: the single branch on every hot path

_SAMPLE_EVERY = 100   # 1 / sample_rate, rounded (counter-based sampling)
_LINEAGE_EVERY = 20   # 1 / lineage_rate
_BUFFER_SPANS = 8192  # per-thread ring capacity
_EXPORT_DIR = "traces"

# per-process clock anchor: monotonic within the process, wall-aligned
# across processes up to OS clock skew (estimate_skew measures the rest)
_T0_WALL = time.time()
_T0_MONO = time.perf_counter()
_PID = os.getpid()


def now() -> float:
    """Anchored wall clock: wall at import + monotonic elapsed since."""
    return _T0_WALL + (time.perf_counter() - _T0_MONO)


# -- id generation ---------------------------------------------------------
_id_lock = threading.Lock()
_id_counter = 0


def _new_id() -> int:
    """Process-unique 63-bit id: (pid << 40) | counter — collision-free
    across the processes of one run without coordination or RNG."""
    global _id_counter
    with _id_lock:
        _id_counter += 1
        return ((_PID & 0x7FFFFF) << 40) | _id_counter


# -- per-thread state: span stack + bounded ring ---------------------------
class _Ring:
    """Fixed-capacity drop-oldest event buffer. ``append`` overwrites the
    oldest un-drained slot when full and counts the casualty."""

    __slots__ = ("buf", "cap", "n", "dropped")

    def __init__(self, cap: int):
        self.cap = max(int(cap), 1)
        self.buf: list = [None] * self.cap
        self.n = 0        # total appended since last drain
        self.dropped = 0  # overwritten before being drained

    def append(self, ev) -> None:
        i = self.n % self.cap
        if self.n >= self.cap:
            self.dropped += 1
        self.buf[i] = ev
        self.n += 1

    def drain(self) -> list:
        """Oldest-first snapshot; clears the ring (drop counter survives
        for ``counters()`` until ``reset()``)."""
        if self.n <= self.cap:
            out = self.buf[: self.n]
        else:
            i = self.n % self.cap
            out = self.buf[i:] + self.buf[:i]
        self.buf = [None] * self.cap
        self.n = 0
        return out


class _ThreadState(threading.local):
    def __init__(self):
        self.ring = _Ring(_BUFFER_SPANS)
        self.stack: list = []     # [(trace_id, span_id), ...]
        self.tick = 0             # sampling counter (span_sampled)
        self.lineage_tick = 0
        self.tid = None           # small per-process thread index
        with _reg_lock:
            _rings.append(self.ring)
            self.tid = len(_rings)
            _tid_names[self.tid] = threading.current_thread().name


_reg_lock = threading.Lock()
_rings: list[_Ring] = []
_tid_names: dict[int, str] = {}
_tls = _ThreadState()

# cross-process clock skew (this process → the server's clock), kept as
# the estimate with the smallest RTT seen (least queueing noise)
_skew_lock = threading.Lock()
_skew_s = 0.0
_skew_rtt_s = float("inf")
_skew_samples = 0


def estimate_skew(t1: float, t2: float, t3: float, t4: float
                  ) -> tuple[float, float]:
    """NTP-style offset of the PEER clock relative to ours, from four
    stamps: t1 our send, t2 peer recv, t3 peer send, t4 our recv.
    Returns ``(offset, rtt)``: peer_clock ≈ our_clock + offset; exact
    when the two network legs are symmetric, off by at most rtt/2."""
    offset = ((t2 - t1) + (t3 - t4)) / 2.0
    rtt = (t4 - t1) - (t3 - t2)
    return offset, rtt


def record_skew(offset_s: float, rtt_s: float) -> None:
    """Keep the minimum-RTT skew estimate (standard NTP filter)."""
    global _skew_s, _skew_rtt_s, _skew_samples
    with _skew_lock:
        _skew_samples += 1
        if rtt_s < _skew_rtt_s:
            _skew_rtt_s = rtt_s
            _skew_s = offset_s


def skew_s() -> float:
    """Best-estimate offset to the server clock (0.0 until measured)."""
    with _skew_lock:
        return _skew_s


def to_server_clock(t: float) -> float:
    return t + skew_s()


# -- spans -----------------------------------------------------------------
class _NullSpan:
    """The disabled path: one shared instance, no allocation, no state."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "trace", "span", "t0")

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        st = _tls
        if st.stack:
            self.trace = st.stack[-1][0]
        else:
            self.trace = _new_id()
        self.span = _new_id()
        st.stack.append((self.trace, self.span))
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        st = _tls
        st.stack.pop()
        parent = st.stack[-1][1] if st.stack else 0
        st.ring.append({
            "name": self.name, "ph": "X",
            "ts": (_T0_WALL + (self.t0 - _T0_MONO)) * 1e6,
            "dur": (t1 - self.t0) * 1e6,
            "pid": _PID, "tid": st.tid,
            "args": {"trace": self.trace, "span": self.span,
                     "parent": parent},
        })
        return False


def span(name: str):
    """Duration span context manager. ``name`` must be in ``STAGES``
    (statically enforced). Disabled → the ``_NULL`` singleton."""
    if not ENABLED:
        return _NULL
    return _Span(name)


def span_sampled(name: str):
    """Like ``span`` but records only every k-th call per thread
    (k = 1/sample_rate) — for per-env-step hot paths where tracing
    every iteration would itself become the bottleneck."""
    if not ENABLED:
        return _NULL
    st = _tls
    st.tick += 1
    if st.tick % _SAMPLE_EVERY:
        return _NULL
    return _Span(name)


def instant(name: str, **args) -> None:
    """Point event (``EVENTS`` table): shed/retry/reconnect/degraded."""
    if not ENABLED:
        return
    st = _tls
    parent = st.stack[-1] if st.stack else (0, 0)
    a = {"trace": parent[0], "span": 0, "parent": parent[1]}
    if args:
        a.update(args)
    st.ring.append({
        "name": name, "ph": "i", "s": "t",
        "ts": now() * 1e6, "dur": 0,
        "pid": _PID, "tid": st.tid, "args": a,
    })


class _Activation:
    """Adopt a remote parent (from ``tr_*`` wire keys) for the handling
    of one request, so server-side spans join the sender's trace."""

    __slots__ = ("ctx",)

    def __init__(self, trace_id: int, span_id: int):
        self.ctx = (trace_id, span_id)

    def __enter__(self):
        _tls.stack.append(self.ctx)
        return self

    def __exit__(self, *exc):
        _tls.stack.pop()
        return False


def activate(req: dict):
    """Context manager joining the sender's trace if the request carries
    context; ``_NULL`` otherwise (disabled, or an un-traced v4 peer)."""
    if not ENABLED:
        return _NULL
    trace_id = req.get(KEY_TRACE)
    if trace_id is None:
        return _NULL
    return _Activation(int(trace_id), int(req.get(KEY_SPAN, 0)))


def wire_context() -> dict:
    """``tr_*`` keys to piggyback on an outgoing request (empty when
    disabled or no span is open — peers treat absence as 'untraced')."""
    if not ENABLED:
        return {}
    st = _tls
    if not st.stack:
        return {}
    trace_id, span_id = st.stack[-1]
    return {KEY_TRACE: trace_id, KEY_SPAN: span_id, KEY_SENT_AT: now()}


class _LockedTracer:
    """``with locked(lock):`` — splits lock WAIT from lock HOLD so
    contention is visible separately from the work under the lock."""

    __slots__ = ("lock", "hold")

    def __init__(self, lock):
        self.lock = lock

    def __enter__(self):
        with _Span("lock_wait"):
            self.lock.acquire()
        self.hold = _Span("lock_hold")
        self.hold.__enter__()
        return self

    def __exit__(self, *exc):
        self.hold.__exit__()
        self.lock.release()
        return False


def locked(lock):
    """Trace-aware lock context: disabled → the lock itself (its native
    ``with`` protocol, zero overhead); enabled → wait/hold split."""
    if not ENABLED:
        return lock
    return _LockedTracer(lock)


def lineage_sample() -> bool:
    """True on every k-th call per thread (k = 1/lineage_rate): the
    caller attaches per-row birth stamps to this flush."""
    if not ENABLED:
        return False
    st = _tls
    st.lineage_tick += 1
    return st.lineage_tick % _LINEAGE_EVERY == 0


# -- configuration ---------------------------------------------------------
def configure(enabled: bool = False, sample_rate: float = 0.01,
              lineage_rate: float = 0.05, buffer_spans: int = 8192,
              export_dir: str = "traces") -> None:
    """Set module state from config values (``cfg.trace``). Safe to call
    before any span is recorded; rings created earlier keep their old
    capacity (threads are long-lived, so configure first)."""
    global ENABLED, _SAMPLE_EVERY, _LINEAGE_EVERY, _BUFFER_SPANS
    global _EXPORT_DIR
    _SAMPLE_EVERY = max(1, int(round(1.0 / max(sample_rate, 1e-9))))
    _LINEAGE_EVERY = max(1, int(round(1.0 / max(lineage_rate, 1e-9))))
    _BUFFER_SPANS = max(int(buffer_spans), 1)
    _EXPORT_DIR = export_dir or "traces"
    ENABLED = bool(enabled)


def configure_from(trace_cfg) -> None:
    """``configure`` from a ``config.TraceConfig`` instance."""
    configure(enabled=trace_cfg.enabled,
              sample_rate=trace_cfg.sample_rate,
              lineage_rate=trace_cfg.lineage_rate,
              buffer_spans=trace_cfg.buffer_spans,
              export_dir=trace_cfg.dir)


def disable() -> None:
    global ENABLED
    ENABLED = False


# -- drain / export / counters ---------------------------------------------
def drain() -> list[dict]:
    """All buffered events from every thread's ring, oldest-first per
    thread; clears the rings (drop counters survive)."""
    out: list[dict] = []
    with _reg_lock:
        rings = list(_rings)
    for r in rings:
        out.extend(r.drain())
    return out


def drop_count() -> int:
    with _reg_lock:
        return sum(r.dropped for r in _rings)


def counters() -> dict[str, float]:
    """Tracer health for the metrics spine (all cheap, all finite)."""
    with _reg_lock:
        dropped = sum(r.dropped for r in _rings)
        buffered = sum(min(r.n, r.cap) for r in _rings)
    with _skew_lock:
        skew_ms = 0.0 if _skew_samples == 0 else _skew_s * 1e3
        samples = _skew_samples
    return {
        "trace/spans_dropped": float(dropped),
        "trace/spans_buffered": float(buffered),
        "trace/clock_skew_ms": round(skew_ms, 3),
        "trace/skew_samples": float(samples),
    }


def reset() -> None:
    """Test hook: clear rings, drop counters, skew, and thread stacks
    registered so far (per-thread stacks of OTHER threads are left to
    unwind naturally)."""
    global _skew_s, _skew_rtt_s, _skew_samples
    with _reg_lock:
        for r in _rings:
            r.drain()
            r.dropped = 0
    with _skew_lock:
        _skew_s, _skew_rtt_s, _skew_samples = 0.0, float("inf"), 0


def export(path: str | None = None) -> str | None:
    """Write this process's buffered events as one Chrome trace-event
    JSON shard (Perfetto-loadable on its own; ``scripts/trace_report.py``
    merges shards and aligns clocks). Returns the path, or None when
    there was nothing to write."""
    events = drain()
    if not events:
        return None
    if path is None:
        os.makedirs(_EXPORT_DIR, exist_ok=True)
        path = os.path.join(_EXPORT_DIR, f"trace-{_PID}.json")
    with _reg_lock:
        names = dict(_tid_names)
    meta = [{"name": "thread_name", "ph": "M", "pid": _PID, "tid": tid,
             "args": {"name": tname}} for tid, tname in names.items()]
    doc = {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "pid": _PID,
            "skew_s": skew_s(),
            "spans_dropped": drop_count(),
            "anchored_at": _T0_WALL,
        },
    }
    tmp = f"{path}.tmp.{_PID}"
    with open(tmp, "w") as fh:       # ddq: allow(durability.raw-write)
        json.dump(doc, fh)           # trace shards are diagnostics, not
        fh.flush()                   # recovery state — a torn shard
        os.fsync(fh.fileno())        # loses a trace, never data
    os.replace(tmp, path)
    return path


# -- attribution (shared by bench --trace-ingest and trace_report) ---------
def self_times(events: list[dict]) -> dict:
    """Per-(pid, tid) SELF-time attribution: for every "X" event, self =
    dur − Σ(direct children) on the same thread. Returns::

        {(pid, tid): {"stages": {name: us}, "counts": {name: n},
                      "wall_us": last_end - first_ts, "traced_us": Σself}}

    The per-thread ``wall_us − traced_us`` gap is the UNTRACED residue —
    surfaced by the report, never hidden.
    """
    by_thread: dict[tuple, list[dict]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        by_thread.setdefault((ev["pid"], ev["tid"]), []).append(ev)
    out: dict = {}
    for key, evs in by_thread.items():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        child: list[float] = [0.0] * len(evs)
        stack: list[int] = []  # indices of open enclosing spans
        for i, ev in enumerate(evs):
            while stack and (evs[stack[-1]]["ts"] + evs[stack[-1]]["dur"]
                             <= ev["ts"] + 1e-9):
                stack.pop()
            if stack:
                child[stack[-1]] += ev["dur"]
            stack.append(i)
        stages: dict[str, float] = {}
        counts: dict[str, int] = {}
        for i, ev in enumerate(evs):
            self_us = max(ev["dur"] - child[i], 0.0)
            stages[ev["name"]] = stages.get(ev["name"], 0.0) + self_us
            counts[ev["name"]] = counts.get(ev["name"], 0) + 1
        first = min(e["ts"] for e in evs)
        last = max(e["ts"] + e["dur"] for e in evs)
        out[key] = {"stages": stages, "counts": counts,
                    "wall_us": last - first,
                    "traced_us": sum(stages.values())}
    return out


def attribution_table(events: list[dict],
                      wall_s: float | None = None) -> str:
    """Human-readable per-stage table over ``self_times``. Per thread:
    each stage's self time, share of thread wall, and the untraced gap
    so 'stages sum to ≈ wall' is checkable at a glance."""
    threads = self_times(events)
    if not threads:
        return "no span events"
    lines = []
    agg: dict[str, float] = {}
    for (pid, tid), t in sorted(threads.items()):
        wall = t["wall_us"]
        if wall_s is not None:
            wall = max(wall, wall_s * 1e6)
        lines.append(f"-- pid {pid} tid {tid} "
                     f"(wall {wall / 1e6:.3f}s, traced "
                     f"{t['traced_us'] / 1e6:.3f}s, coverage "
                     f"{100.0 * t['traced_us'] / max(wall, 1e-9):.1f}%)")
        lines.append(f"   {'stage':<18}{'self_ms':>12}{'count':>8}"
                     f"{'share':>8}")
        for name, us in sorted(t["stages"].items(), key=lambda kv: -kv[1]):
            agg[name] = agg.get(name, 0.0) + us
            lines.append(f"   {name:<18}{us / 1e3:>12.2f}"
                         f"{t['counts'][name]:>8}"
                         f"{100.0 * us / max(wall, 1e-9):>7.1f}%")
        gap = max(wall - t["traced_us"], 0.0)
        lines.append(f"   {'(untraced)':<18}{gap / 1e3:>12.2f}{'':>8}"
                     f"{100.0 * gap / max(wall, 1e-9):>7.1f}%")
    lines.append("-- all threads (self time)")
    total = sum(agg.values())
    for name, us in sorted(agg.items(), key=lambda kv: -kv[1]):
        lines.append(f"   {name:<18}{us / 1e3:>12.2f}{'':>8}"
                     f"{100.0 * us / max(total, 1e-9):>7.1f}%")
    return "\n".join(lines)
