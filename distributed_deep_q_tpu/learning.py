"""Learning-dynamics plane (ISSUE 16): on-device metric accumulators.

The fused-chain train scan (``parallel/learner.py``) and the Anakin
superstep (``parallel/anakin.py``) deliberately run with ZERO host
communication, which made them observability black holes: loss, TD
error, grad norm, Q-value scale, and the PER sampling distribution were
invisible exactly where ROADMAP items 2 and 5 need them. This module is
the bridge — a small flat f32 **metrics plane** that rides the existing
scan carry, accumulated per grad step with plain ``jnp`` (no
infeed/outfeed/callback ops, so the Anakin zero-host-comm census still
holds), finalized ONCE per dispatch with the chunk's collectives, and
returned as a normal program output the host folds at its own cadence.

Plane layout (one f32 vector, ``PLANE_SIZE`` elements)::

    [0:N_HIST]      TD-|error| log-bucket counts — geometry is an exact
                    twin of ``metrics.Histogram(TD_LO, TD_HI,
                    TD_PER_DECADE)`` so the host can pour the counts
                    straight into the PR 12 merge/delta machinery
    psum sums       shard-local sums: Σ|TD|, Σ sampled priority
                    ((|TD|+ε)^α — the scatter_priorities value), Σ IS
                    weight, sample count
    repl sums       already-replicated per-step scalars (loss, grad
                    norm pre/post clip, Q mean, target-refresh count,
                    non-finite-loss count, step count) — summed as-is,
                    NOT psum'd again
    maxes           shard-local extrema: max |TD|, max Q, max priority
    mins            min IS weight, min |TD|

``lm_finalize`` makes the plane truly replicated (psum the shard-local
segment, pmax/pmin the extrema) so it can leave the ``shard_map`` under
an ordinary ``P()`` out-spec. Everything is gated behind the STATIC
``cfg.train.learn_metrics`` flag: off traces zero extra ops — the
compiled programs are bitwise identical to pre-PR (pinned by
tests/test_learning_metrics.py and the test_op_count.py ratchets).

Host side, ``LearnAccumulator`` folds returned planes (cumulative +
sliding window), rebuilds the TD histogram as a real
``metrics.Histogram``, and publishes ``learn/*`` gauges that feed the
PR 12 health plane (``health.default_learn_rules/trends``) and the run
JSONL.
"""

from __future__ import annotations

import math
import threading

import numpy as np

from distributed_deep_q_tpu.metrics import Histogram

# TD-|error| histogram geometry — must stay in lockstep with the host
# Histogram the accumulator rebuilds (pinned by test_learning_metrics).
# |TD| for clipped-reward DQN lives overwhelmingly in [1e-3, 1e2]; four
# buckets/decade over eight decades is enough shape at 34 floats.
TD_LO = 1e-4
TD_HI = 1e4
TD_PER_DECADE = 4
_LOG_LO = math.log(TD_LO)
_SCALE = TD_PER_DECADE / math.log(10.0)
# interior + underflow + overflow — same derivation as Histogram.__init__
N_HIST = int(math.ceil((math.log(TD_HI) - _LOG_LO) * _SCALE)) + 2

# scalar slots after the histogram segment
I_TD_SUM = N_HIST + 0        # Σ|TD| over samples          (psum)
I_PRIO_SUM = N_HIST + 1      # Σ(|TD|+ε)^α                 (psum)
I_ISW_SUM = N_HIST + 2       # Σ IS weight                 (psum)
I_SAMPLES = N_HIST + 3       # sample count                (psum)
I_LOSS_SUM = N_HIST + 4      # Σ loss (already pmean'd)    (replicated)
I_GNORM_SUM = N_HIST + 5     # Σ grad norm pre-clip        (replicated)
I_GNORM_CLIP_SUM = N_HIST + 6  # Σ grad norm post-clip     (replicated)
I_QMEAN_SUM = N_HIST + 7     # Σ Q mean (already pmean'd)  (replicated)
I_REFRESH = N_HIST + 8       # target-refresh count        (replicated)
I_NONFINITE = N_HIST + 9     # non-finite-loss step count  (replicated)
I_STEPS = N_HIST + 10        # grad-step count             (replicated)
I_TD_MAX = N_HIST + 11       # max |TD|                    (pmax)
I_Q_MAX = N_HIST + 12        # max Q                       (pmax)
I_PRIO_MAX = N_HIST + 13     # max sampled priority        (pmax)
I_ISW_MIN = N_HIST + 14      # min IS weight               (pmin)
I_TD_MIN = N_HIST + 15       # min |TD|                    (pmin)
PLANE_SIZE = N_HIST + 16

# segment boundaries for finalize/fold: [0, _REPL) psums, [_REPL, _MAX)
# rides through replicated, [_MAX, _MIN) pmax, [_MIN, end) pmin
_REPL = I_LOSS_SUM
_MAX = I_TD_MAX
_MIN = I_ISW_MIN


# -- device side (pure jnp; traced only when cfg.learn_metrics) -------------
def lm_init():
    """Fresh per-dispatch plane: zero sums, ∓inf extrema identities."""
    import jax.numpy as jnp

    z = jnp.zeros((PLANE_SIZE,), jnp.float32)
    z = z.at[_MAX:_MIN].set(-jnp.inf)
    return z.at[_MIN:].set(jnp.inf)


def lm_update(plane, *, cfg, td_abs, weight, loss, q, q_mean, gnorm,
              step, alpha, eps):
    """Fold one grad step into the plane — elementwise jnp only.

    ``td_abs``/``weight``/``q`` are SHARD-LOCAL per-sample arrays;
    ``loss``/``q_mean`` arrive already ``pmean``'d (replicated) and
    ``gnorm`` is computed from the allreduced gradient, so those sums
    land in the replicated segment that ``lm_finalize`` does NOT psum.
    ``alpha``/``eps`` are the replay's PER exponent/floor, so the
    priority statistic is exactly the value ``scatter_priorities``
    writes back. Non-finite inputs are squashed (``nan_to_num``) so one
    diverged step cannot poison the whole window — the divergence
    itself is what ``I_NONFINITE`` counts.
    """
    import jax.numpy as jnp

    td = jnp.nan_to_num(td_abs.astype(jnp.float32).reshape(-1),
                        nan=0.0, posinf=TD_HI * 10.0, neginf=0.0)
    w = jnp.nan_to_num(weight.astype(jnp.float32).reshape(-1),
                       nan=0.0, posinf=0.0, neginf=0.0)
    qf = jnp.nan_to_num(q.astype(jnp.float32), nan=0.0,
                        posinf=0.0, neginf=0.0)
    finite = jnp.isfinite(loss)
    loss_s = jnp.where(finite, loss, 0.0)
    gnorm_s = jnp.where(jnp.isfinite(gnorm), gnorm, 0.0)
    qmean_s = jnp.where(jnp.isfinite(q_mean), q_mean, 0.0)

    # log-bucket index — the jnp twin of Histogram.observe (floor ==
    # int-truncation here: the argument is non-negative once v >= lo)
    safe = jnp.maximum(td, TD_LO)
    idx = 1 + jnp.floor(
        (jnp.log(safe) - _LOG_LO) * _SCALE).astype(jnp.int32)
    idx = jnp.where(td < TD_LO, 0, jnp.minimum(idx, N_HIST - 1))
    plane = plane.at[idx].add(1.0)

    prio = (td + eps) ** alpha
    clip = float(cfg.grad_clip_norm)
    scale = (jnp.minimum(1.0, clip / jnp.maximum(gnorm_s, 1e-12))
             if clip > 0 else jnp.float32(1.0))
    if cfg.target_tau > 0:
        refresh = jnp.float32(1.0)  # Polyak: every step refreshes
    else:
        refresh = (step % cfg.target_update_period == 0).astype(
            jnp.float32)
    sums = jnp.stack([
        jnp.sum(td), jnp.sum(prio), jnp.sum(w),
        jnp.float32(td.shape[0]),
        loss_s, gnorm_s, gnorm_s * scale, qmean_s, refresh,
        1.0 - finite.astype(jnp.float32), jnp.float32(1.0)])
    plane = plane.at[I_TD_SUM:I_TD_SUM + sums.shape[0]].add(sums)
    plane = plane.at[_MAX:_MIN].max(
        jnp.stack([jnp.max(td), jnp.max(qf), jnp.max(prio)]))
    return plane.at[_MIN:].min(jnp.stack([jnp.min(w), jnp.min(td)]))


def lm_finalize(plane, axis):
    """ONE cross-shard reduction per dispatch, after the scan: psum the
    shard-local counts/sums, pmax/pmin the extrema, pass the
    already-replicated segment through — the result is truly replicated
    and legal under a ``P()`` out-spec."""
    import jax.numpy as jnp
    from jax import lax

    return jnp.concatenate([
        lax.psum(plane[:_REPL], axis), plane[_REPL:_MAX],
        lax.pmax(plane[_MAX:_MIN], axis), lax.pmin(plane[_MIN:], axis)])


# -- host side --------------------------------------------------------------
def host_plane() -> np.ndarray:
    """The fold identity, as f64 numpy (counts stay exact far past the
    f32 2^24 integer ceiling once folded on the host)."""
    z = np.zeros(PLANE_SIZE, np.float64)
    z[_MAX:_MIN] = -np.inf
    z[_MIN:] = np.inf
    return z


def fold_plane(dst: np.ndarray, plane) -> np.ndarray:
    """Fold one or more returned planes (``[PLANE_SIZE]`` or any
    leading-dim stack) into ``dst`` in place — sums add, extrema
    max/min, exactly the device combine."""
    p = np.asarray(plane, np.float64).reshape(-1, PLANE_SIZE)
    dst[:_MAX] += p[:, :_MAX].sum(axis=0)
    np.maximum(dst[_MAX:_MIN], p[:, _MAX:_MIN].max(axis=0),
               out=dst[_MAX:_MIN])
    np.minimum(dst[_MIN:], p[:, _MIN:].min(axis=0), out=dst[_MIN:])
    return dst


def plane_histogram(plane: np.ndarray) -> Histogram:
    """Rebuild the TD-|error| histogram as a real ``metrics.Histogram``
    — counts poured straight into the PR 12 merge/snapshot/delta
    machinery, total/extrema restored from the plane's scalar slots."""
    h = Histogram(TD_LO, TD_HI, TD_PER_DECADE)
    counts = [int(round(c)) for c in np.asarray(plane[:N_HIST])]
    assert len(counts) == len(h._counts), "plane/Histogram geometry drift"
    h._counts = counts
    h.count = sum(counts)
    h.total = float(plane[I_TD_SUM])
    if h.count:
        h.vmin = float(plane[I_TD_MIN])
        h.vmax = float(plane[I_TD_MAX])
    return h


class LearnAccumulator:
    """Host fold of learning-dynamics planes: cumulative totals (the TD
    histogram the report reads) plus a sliding window that turns into
    fresh ``learn/*`` gauges each ``gauges()`` call — the per-tick
    points the health plane's divergence trends compare.

    One lock guards all mutable state: ``ingest`` runs on the training
    loop's dispatch cadence while ``gauges``/``hist_snapshot`` answer
    the supervisor's log tick and the fleet's ``health`` scrape thread.
    """

    def __init__(self):
        self._lm_lock = threading.Lock()
        self._lm_total = host_plane()
        self._lm_window = host_plane()
        self._lm_planes = 0
        self._lm_last: dict[str, float] = {}

    def ingest(self, plane) -> None:
        """Fold one dispatch's returned plane (numpy or device array —
        conversion happens here, at log cadence, never per step)."""
        if plane is None:
            return
        with self._lm_lock:
            fold_plane(self._lm_total, plane)
            fold_plane(self._lm_window, plane)
            self._lm_planes += 1

    @property
    def planes(self) -> int:
        with self._lm_lock:
            return self._lm_planes

    def hist_snapshot(self) -> Histogram:
        """Cumulative TD histogram — monotone, so ``HealthMonitor``'s
        snapshot/delta windowing applies unchanged."""
        with self._lm_lock:
            return plane_histogram(self._lm_total)

    def gauges(self) -> dict[str, float]:
        """Drain the window into one flat ``learn/*`` gauge dict; with
        no new planes since the last call the previous gauges are
        re-published (a stalled learner should hold its last readings,
        not flap to zero)."""
        with self._lm_lock:
            w = self._lm_window
            steps = w[I_STEPS]
            if steps <= 0:
                return dict(self._lm_last)
            samples = max(w[I_SAMPLES], 1.0)
            out = {
                "learn/loss": w[I_LOSS_SUM] / steps,
                "learn/grad_norm": w[I_GNORM_SUM] / steps,
                "learn/grad_norm_clipped": w[I_GNORM_CLIP_SUM] / steps,
                "learn/q_mean": w[I_QMEAN_SUM] / steps,
                "learn/q_max": w[I_Q_MAX],
                "learn/td_mean": w[I_TD_SUM] / samples,
                "learn/td_max": w[I_TD_MAX],
                "learn/prio_mean": w[I_PRIO_SUM] / samples,
                "learn/prio_max": w[I_PRIO_MAX],
                "learn/is_weight_mean": w[I_ISW_SUM] / samples,
                "learn/is_weight_min": w[I_ISW_MIN],
                "learn/target_refreshes": w[I_REFRESH],
                "learn/loss_nonfinite": w[I_NONFINITE],
                "learn/steps": self._lm_total[I_STEPS],
            }
            out = {k: float(v) for k, v in out.items()}
            self._lm_window = host_plane()
            self._lm_last = out
            return dict(out)


def learn_scrape_fn(acc: LearnAccumulator, monitor):
    """The learner's fleet-member ``health`` endpoint: sample the
    accumulator's gauges + TD-histogram snapshot into ``monitor`` and
    answer the wire verdict — the same closure shape ``FleetHealth``
    registers for the in-process replay member."""
    def _scrape() -> dict:
        return monitor.scrape(acc.gauges(),
                              {"learn/td_error": acc.hist_snapshot()})
    return _scrape
