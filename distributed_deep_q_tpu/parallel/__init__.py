from distributed_deep_q_tpu.parallel.mesh import make_mesh, mesh_devices  # noqa: F401
from distributed_deep_q_tpu.parallel.learner import Learner, TrainState  # noqa: F401
