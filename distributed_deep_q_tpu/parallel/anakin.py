"""Anakin mode — acting, replay insert, and training in ONE jitted program.

The Podracer paper's Anakin endpoint (PAPERS.md arXiv:2104.06272) puts the
environment ON the accelerator: when the env's step function is expressible
in ``jax.numpy`` (the ``signal_atari`` family — ``ops/jax_envs.py``), the
whole act→insert→learn loop compiles into a single ``shard_map``ped XLA
program and the host's only steady-state job is re-dispatching it. This is
a MODE of the existing system, not a fork:

- the replay ring is the SAME ``DevicePERFrameReplay`` allocation the
  distributed path trains from (padded frame plane, ghost rows, metadata/
  priority rows, Pallas row-DMA insert via ``insert_meta_pack`` +
  ``scatter_rows``) — only the cursor/size bookkeeping moves from host
  slot objects into the device carry;
- the train phase is the learner's plane-carry body (``plane_train_fn``,
  PERF.md §3) recomposed from the same primitives — ``fused_sample_prep``
  → ``build_meta_pack`` → ``fused_sample_draw_packed`` →
  ``gather_windows`` → ``stacked_q_apply`` → ``q_step_loss`` →
  ``fused_plane_adam_target_step`` → ``scatter_priorities`` — with θ/θ⁻
  and the Adam moments living PERMANENTLY as flat planes in the donated
  carry (the distributed path converts tree↔plane at every chunk
  boundary; here the conversion happens once at construction and once at
  ``sync_solver``);
- sampling keys and β stay host-generated per dispatch
  (``sample_key_schedule`` — same schedule, same anchoring as the
  distributed fused path), so a fold_in-keyed program never touches the
  ring gather (measured ~200× slower, learner.py r3 note). They ride in
  as tiny arguments; nothing is read back.

Superstep layout (one dispatch, donated carry)::

    act scan (T ticks):   vmapped jax env step + batched ε-greedy forward
                          through the online half of the parameter plane
    ring insert:          T·E staged rows per shard → one meta-pack +
                          row-DMA scatter (ghost mirroring, device cursors)
    sample (hoisted):     chunk CDF + pack + all-chain draws + window DMA
    train scan (chain):   the plane-carry grad step + priority scatter

Env↔slot identity: with ``num_envs == num_slots`` every env owns exactly
ONE sub-ring, so the stream→slot advance of the host path degenerates to
the identity and the device cursor math is ``cursor = (cursor + T) %
slot_cap``. Env at plane position ``p`` of shard ``d`` is global stream
``gid = sub·D + d`` — the SAME routing ``DeviceFrameReplay._slot_base``
gives ``add_batch(stream=gid)``, which is what makes the Anakin ring
bitwise-comparable to a host loop feeding the same transitions
(tests/test_anakin.py).

Zero steady-state host transfers: the compiled superstep contains no
infeed/outfeed/send/recv/host-copy ops (pinned via ``profiling.py``'s HLO
census in tests/test_op_count.py, alongside the scheduled-op ratchet).
Episode returns and train metrics come back as replicated device scalars
the caller may read at its OWN cadence — reading is the only D2H, and it
is optional.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_deep_q_tpu import learning, tracing
from distributed_deep_q_tpu.compat import shard_map
from distributed_deep_q_tpu.config import Config
from distributed_deep_q_tpu.models.qnet import stacked_q_apply
from distributed_deep_q_tpu.ops.jax_envs import make_jax_env
from distributed_deep_q_tpu.ops.ring_gather import (
    gather_windows, scatter_rows)
from distributed_deep_q_tpu.parallel.learner import (
    TrainState, _locate_adam_state, fused_plane_adam_target_step,
    params_to_plane, plane_meta, plane_stacked_views, plane_to_param_trees,
    plane_to_tree, q_step_loss, tree_to_plane)
from distributed_deep_q_tpu.parallel.mesh import AXIS_DP, AXIS_MODEL
from distributed_deep_q_tpu.replay.device_per import (
    DeviceReplayState, build_meta_pack, fused_sample_draw_packed,
    fused_sample_prep, insert_meta_pack, scatter_priorities,
    stack_rows_to_obs)


def act_tick(apply_fn, step_fn, frame_shape, params, eps, env_state, buf,
             akeys):
    """One vectorized ε-greedy acting tick over ``n`` co-resident envs.

    THE single copy of the per-tick acting math, shared verbatim by the
    Anakin superstep's act scan and the host reference driver in
    tests/test_anakin.py — the bitwise ring pin compares two drivers of
    this exact function, so acting semantics can never fork between them.

    ``buf`` is the batched frame stacker ``[n, stack, H·W]`` u8 (newest
    frame last — the device twin of ``FrameStacker``/
    ``VectorFrameStacker``); ``akeys`` per-env action keys ``[n, 2]``;
    ``eps`` the per-env ε ladder ``[n]``. Episode boundaries fold into the
    tick exactly like the host loops: the env auto-resets inside ``step``
    (``ops/jax_envs.py``) and the stacker row restarts from the new
    episode's first frame (zeros + that frame — ``FrameStacker.reset``).

    Returns ``(env_state, buf, akeys, record)`` where ``record`` holds the
    transition row the host actor would flush: the PRE-step frame, the
    action, reward, and the done flag (the signal envs terminate on their
    step cap, so done doubles as the episode boundary — the same value the
    numpy envs return for both).
    """
    n, stack = buf.shape[0], buf.shape[1]
    h, w = frame_shape
    obs = jnp.moveaxis(buf.reshape(n, stack, h, w), 1, -1)
    q = apply_fn(params, obs)
    num_actions = q.shape[-1]
    greedy = jnp.argmax(q, axis=-1).astype(jnp.int32)
    k3 = jax.vmap(lambda k: jax.random.split(k, 3))(akeys)     # [n, 3, 2]
    akeys, ku, kr = k3[:, 0], k3[:, 1], k3[:, 2]
    u = jax.vmap(jax.random.uniform)(ku)
    ra = jax.vmap(
        lambda k: jax.random.randint(k, (), 0, num_actions, jnp.int32))(kr)
    action = jnp.where(u < eps, ra, greedy)
    env_state, frame, reward, done = jax.vmap(step_fn)(env_state, action)
    frow = frame.reshape(n, -1)
    pushed = jnp.concatenate([buf[:, 1:], frow[:, None]], axis=1)
    fresh = jnp.concatenate(
        [jnp.zeros_like(buf[:, 1:]), frow[:, None]], axis=1)
    record = {"frame": buf[:, -1], "action": action,
              "reward": reward.astype(jnp.float32), "done": done}
    buf = jnp.where(done[:, None, None], fresh, pushed)
    return env_state, buf, akeys, record


class AnakinRunner:
    """Owner of the Anakin superstep: carry allocation, dispatch, and the
    tree↔plane seams back into the ``Solver``.

    Construction derives everything from the SAME config the distributed
    path reads: ``cfg.actors.anakin_envs`` co-resident envs (must divide
    over the dp mesh; 0 = one per shard), ``cfg.actors.anakin_ticks`` env
    ticks per superstep, ``cfg.replay.fused_chain`` grad steps per
    superstep, the Ape-X ε ladder from ``eps_base``/``eps_alpha`` keyed by
    global stream id. All envs run ``cfg.env`` (one jax step function is
    vmapped — multi-game fleets stay on the host acting planes).

    The donated device carry holds: the ``DeviceReplayState`` ring twin,
    vmapped env states, the batched stacker buffer, per-env action keys,
    per-sub cursors/sizes, and the θ/θ⁻ + Adam planes. ``superstep()``
    dispatches one act+insert+train program; ``sync_solver()`` folds the
    planes back into ``solver.state`` so checkpoints, ``q_values``, and
    weight publishing keep working unchanged — the mode seam.
    """

    def __init__(self, cfg: Config, solver=None, replay=None):
        from distributed_deep_q_tpu.actors.supervisor import actor_epsilon
        from distributed_deep_q_tpu.replay.device_per import (
            DevicePERFrameReplay)
        from distributed_deep_q_tpu.solver import Solver

        self.cfg = cfg
        h, w = cfg.env.frame_shape
        stack = int(cfg.env.stack)
        self.frame_shape = (h, w)
        self.solver = solver or Solver(cfg, obs_dim=h * w * stack)
        mesh = self.solver.mesh
        assert cfg.train.optimizer == "adam" and \
            mesh.shape[AXIS_MODEL] <= 1, (
                "Anakin reuses the plane-carry train body, which requires "
                "adam and no model-parallel axis (learner.py use_plane)")
        d = mesh.shape[AXIS_DP]
        n = int(cfg.actors.anakin_envs) or d
        assert n % d == 0, f"anakin_envs={n} must divide over {d} dp shards"
        self.num_envs, self.num_shards = n, d
        self.envs_per_shard = n // d
        self.replay = replay or DevicePERFrameReplay(
            cfg.replay, mesh, self.frame_shape, stack, cfg.train.gamma,
            seed=cfg.train.seed, write_chunk=cfg.replay.write_chunk,
            num_streams=n)
        rp = self.replay
        assert rp.num_slots == n and rp.subs_per_shard == n // d, (
            "env↔slot identity needs one slot per env: raise anakin_envs "
            "to a multiple of the dp shard count")
        self.ticks = int(cfg.actors.anakin_ticks)
        assert 0 < self.ticks <= rp.slot_cap, (
            f"anakin_ticks={self.ticks} must stay within one sub-ring "
            f"(slot_cap={rp.slot_cap}) so a superstep's row targets are "
            "distinct")
        self.chain = max(int(cfg.replay.fused_chain), 1)
        assert cfg.replay.batch_size % d == 0

        # env at plane position p = shard·E + e is global stream e·D + d —
        # DeviceFrameReplay's slot s ↔ (shard s % D, sub s // D) routing,
        # which add_batch(stream=gid) follows when num_streams == num_slots
        e_per = self.envs_per_shard
        self.stream_ids = np.array(
            [(p % e_per) * d + (p // e_per) for p in range(n)], np.int64)
        eps = np.array(
            [actor_epsilon(int(g), n, cfg.actors.eps_base,
                           cfg.actors.eps_alpha) for g in self.stream_ids],
            np.float32)

        sharded = NamedSharding(mesh, P(AXIS_DP))
        self._eps = jax.device_put(eps, sharded)
        self._reset_fn, self._step_fn = make_jax_env(cfg.env)

        # per-env key streams echo the numpy fleet's seed-offset discipline
        # (env 1000·(gid+1), ε 7777·(gid+1)) in the jax.random family —
        # deterministic and collision-free, but deliberately NOT numpy-rng
        # parity (ops/jax_envs.py docstring)
        base = jax.random.PRNGKey(cfg.train.seed)
        env_keys = jax.vmap(
            lambda g: jax.random.fold_in(base, 1000 * (g + 1)))(
                jnp.asarray(self.stream_ids, jnp.int32))
        self.act_keys0 = jax.vmap(
            lambda g: jax.random.fold_in(base, 7777 * (g + 1)))(
                jnp.asarray(self.stream_ids, jnp.int32))

        row_len = rp._row_len
        reset_fn = self._reset_fn

        def _init(ekeys, akeys):
            st, frame = jax.vmap(reset_fn)(ekeys)
            buf = jnp.zeros((n, stack, row_len), jnp.uint8)
            buf = buf.at[:, -1].set(frame.reshape(n, -1))
            return st, buf, akeys

        shapes = jax.eval_shape(_init, env_keys, self.act_keys0)
        env_state, buf, akeys = jax.jit(
            _init, out_shardings=jax.tree.map(lambda _: sharded, shapes))(
                env_keys, self.act_keys0)
        self._env_spec = jax.tree.map(lambda _: P(AXIS_DP), shapes[0])

        # θ/θ⁻ + Adam moments as persistent planes (the distributed path
        # pays this conversion per chunk; Anakin pays it here and at sync)
        state = self.solver.state
        self._meta = plane_meta(state.params)
        adam_state, _ = _locate_adam_state(state.opt_state)
        repl = NamedSharding(mesh, P())
        pt, m, v = jax.jit(
            lambda s, a: (params_to_plane(self._meta, s.params,
                                          s.target_params),
                          tree_to_plane(a.mu), tree_to_plane(a.nu)),
            out_shardings=(repl, repl, repl))(state, adam_state)
        cursors = jax.device_put(np.zeros(n, np.int32), sharded)
        sizes = jax.device_put(np.zeros(n, np.int32), sharded)
        self._carry = (rp.dstate, env_state, buf, akeys, cursors, sizes,
                       pt, m, v, adam_state.count, state.step)
        rp.dstate = None  # single owner: the ring lives in the carry now
        self._fn = self._build_superstep(mesh)
        self.last_metrics: dict[str, Any] | None = None
        self.last_act_reward: Any = None
        self.supersteps_run = 0

    # -- the program ---------------------------------------------------------

    def _build_superstep(self, mesh):
        cfg_t = self.cfg.train
        rp = self.replay
        slot_cap, slot_pad = rp.slot_cap, rp.slot_pad
        rowb, row_len, rowp = rp.rowb, rp._row_len, rp.rowb // 4
        stack, n_step, gamma = rp.stack, rp.n_step, rp.gamma
        window = stack + n_step
        scratch = rp.cap_local_pad
        interpret = rp._interpret
        d, e_per, t_len = self.num_shards, self.envs_per_shard, self.ticks
        k = t_len * e_per
        chain = self.chain
        per_b = self.cfg.replay.batch_size // d
        alpha = float(self.cfg.replay.priority_alpha)
        p_eps = float(self.cfg.replay.priority_eps)
        n_win = chain * per_b
        apply_fn = self.solver.apply_fn
        meta = self._meta
        step_fn = self._step_fn
        frame_shape = self.frame_shape
        double = cfg_t.double_dqn

        def superstep_body(carry, eps, keys, betas):
            (ds, env_st, buf, akeys, cursors, sizes,
             pt, m, v, cnt, gstep) = carry

            # -- act scan: T ticks against this superstep's frozen θ ------
            params = jax.tree_util.tree_unflatten(
                meta.treedef, [x[0] for x in plane_stacked_views(meta, pt)])

            def act_body(c, _):
                env_st, buf, akeys = c
                env_st, buf, akeys, rec = act_tick(
                    apply_fn, step_fn, frame_shape, params, eps, env_st,
                    buf, akeys)
                return (env_st, buf, akeys), rec

            (env_st, buf, akeys), recs = lax.scan(
                act_body, (env_st, buf, akeys), None, length=t_len)

            # -- ring insert: one meta pack + row-DMA scatter per shard ---
            # (the device twin of _apply_write's main/ghost/scratch didx)
            t_i = jnp.arange(t_len, dtype=jnp.int32)[:, None]
            e_i = jnp.arange(e_per, dtype=jnp.int32)[None, :]
            local = (cursors[None, :] + t_i) % slot_cap          # [T, E]
            midx = (e_i * slot_cap + local).reshape(-1)
            main = e_i * slot_pad + local
            ghost = jnp.where(local < window - 1,
                              e_i * slot_pad + slot_cap + local, scratch)
            sidx = jnp.concatenate(
                [jnp.arange(k, dtype=jnp.int32)] * 2)
            didx = jnp.concatenate([main.reshape(-1), ghost.reshape(-1)])
            packed, new_p = insert_meta_pack(
                recs["frame"].reshape(-1), ds.maxp, k=k, row_len=row_len,
                rowb=rowb, alpha=alpha)
            frames = scatter_rows(sidx, didx, packed, ds.frames, n=2 * k,
                                  rowb=rowb, interpret=interpret)
            dn = recs["done"].reshape(-1).astype(jnp.uint8)
            action = ds.action.at[midx].set(
                recs["action"].reshape(-1).astype(jnp.int32))
            reward = ds.reward.at[midx].set(recs["reward"].reshape(-1))
            done = ds.done.at[midx].set(dn)
            boundary = ds.boundary.at[midx].set(dn)
            prio = ds.prio.at[midx].set(new_p)
            cursors = (cursors + t_len) % slot_cap
            sizes = jnp.minimum(sizes + t_len, slot_cap)

            # -- sample, hoisted per chunk (learner.py sample_fn twin) ----
            shard_rows = {"action": action, "reward": reward, "done": done,
                          "boundary": boundary, "prio": prio}
            pm, cdf, mass, n_glob = fused_sample_prep(
                shard_rows, cursors, sizes, slot_cap, stack, n_step)
            pack = build_meta_pack(action, reward, done, boundary,
                                   slot_cap, stack, n_step, gamma)
            metas, ws, idxs = fused_sample_draw_packed(
                keys[0], pack, pm, cdf, mass, n_glob, per_b, slot_cap,
                slot_pad, stack, n_step, betas, d)
            win = gather_windows(ws.reshape(-1), frames, n=n_win, w=window,
                                 rowb=rowb, interpret=interpret)
            win = win.reshape(chain, per_b, window, rowp)

            # -- train scan: the plane-carry body (plane_train_fn twin) ---
            def train_body(c, xs):
                if cfg_t.learn_metrics:
                    pt, m, v, cnt, gstep, prio, maxp, lmp = c
                else:
                    pt, m, v, cnt, gstep, prio, maxp = c
                batch, w_, idx = xs
                batch = dict(batch)
                ovalid = batch.pop("ovalid")
                nvalid = batch.pop("nvalid")
                pix = lax.bitcast_convert_type(w_, jnp.uint8)
                pix = pix.reshape(w_.shape[:2] + (rowp * 4,))[:, :, :row_len]
                obs = pix[:, :stack] * ovalid[..., None]
                nobs = pix[:, n_step:n_step + stack] * nvalid[..., None]
                batch["obs"] = stack_rows_to_obs(obs, frame_shape)
                batch["next_obs"] = stack_rows_to_obs(nobs, frame_shape)
                step2 = gstep + 1

                def loss_fn(views):
                    stacked = jax.tree_util.tree_unflatten(
                        meta.treedef, list(views))
                    q, q_next_o, q_next_t = stacked_q_apply(
                        apply_fn, stacked, batch["obs"], batch["next_obs"],
                        double)
                    loss, td_abs = q_step_loss(cfg_t, q, q_next_o,
                                               q_next_t, batch)
                    return loss, (td_abs, q)

                (loss, (td_abs, q)), gv = jax.value_and_grad(
                    loss_fn, has_aux=True)(plane_stacked_views(meta, pt))
                g = jnp.concatenate([x[0].reshape(-1) for x in gv])
                g = lax.pmean(g, AXIS_DP)
                loss = lax.pmean(loss, AXIS_DP)
                q_mean = lax.pmean(jnp.mean(q), AXIS_DP)
                gnorm = jnp.sqrt(jnp.sum(jnp.square(g)))
                m, v, pt, cnt = fused_plane_adam_target_step(
                    cfg_t, meta, g, m, v, cnt, pt, step2, gnorm)
                prio, maxp = scatter_priorities(prio, maxp, idx, td_abs,
                                                alpha, p_eps)
                metrics = {"loss": loss, "q_mean": q_mean,
                           "grad_norm": gnorm}
                if cfg_t.learn_metrics:
                    # learning-dynamics plane (learning.py): jnp-only
                    # accumulation, so the zero-host-comm census pin
                    # holds with the gate on (test_op_count)
                    lmp = learning.lm_update(
                        lmp, cfg=cfg_t, td_abs=td_abs,
                        weight=batch["weight"], loss=loss, q=q,
                        q_mean=q_mean, gnorm=gnorm, step=step2,
                        alpha=alpha, eps=p_eps)
                    return (pt, m, v, cnt, step2, prio, maxp, lmp), \
                        metrics
                return (pt, m, v, cnt, step2, prio, maxp), metrics

            carry0 = (pt, m, v, cnt, gstep, prio, ds.maxp)
            if cfg_t.learn_metrics:
                carry0 = carry0 + (learning.lm_init(),)
                (pt, m, v, cnt, gstep, prio, maxp, lmp), metrics = \
                    lax.scan(train_body, carry0, (metas, win, idxs))
                metrics = dict(metrics)
                metrics["learn_plane"] = learning.lm_finalize(
                    lmp, AXIS_DP)
            else:
                (pt, m, v, cnt, gstep, prio, maxp), metrics = lax.scan(
                    train_body, carry0, (metas, win, idxs))

            ds = DeviceReplayState(
                frames=frames, action=action, reward=reward, done=done,
                boundary=boundary, prio=prio, maxp=maxp)
            act_reward = lax.pmean(jnp.mean(recs["reward"]), AXIS_DP)
            return ((ds, env_st, buf, akeys, cursors, sizes,
                     pt, m, v, cnt, gstep), metrics, act_reward)

        S = P(AXIS_DP)
        state_spec = DeviceReplayState(
            frames=S, action=S, reward=S, done=S, boundary=S, prio=S,
            maxp=P())
        carry_spec = (state_spec, self._env_spec, S, S, S, S,
                      P(), P(), P(), P(), P())
        metric_spec = {"loss": P(), "q_mean": P(), "grad_norm": P()}
        if cfg_t.learn_metrics:
            # the finalized plane is replicated (lm_finalize's psums)
            metric_spec["learn_plane"] = P()
        return jax.jit(
            shard_map(superstep_body, mesh=mesh,
                      in_specs=(carry_spec, S, S, P()),
                      out_specs=(carry_spec, metric_spec, P()),
                      check_vma=False),
            donate_argnums=(0,))

    # -- dispatch ------------------------------------------------------------

    def superstep(self) -> dict[str, Any]:
        """One act+insert+train dispatch. Keys/β are the distributed fused
        path's exact schedules (``next_fused_keys`` anchoring via the
        solver, ``next_betas`` via the replay), so an Anakin run and a
        host-driven run of the same config draw identical samples. The
        span times host DISPATCH only — nothing blocks, nothing reads
        back; returned metrics are ``[chain]`` device arrays."""
        keys = self.solver._next_sample_keys(self.num_shards, self.chain)
        betas = np.asarray(self.replay.next_betas(self.chain), np.float32)
        with tracing.span("anakin_superstep"):
            self._carry, metrics, act_r = self._fn(
                self._carry, self._eps, keys, betas)
        self.last_metrics, self.last_act_reward = metrics, act_r
        self.supersteps_run += 1
        return metrics

    def run(self, supersteps: int) -> dict[str, Any]:
        """Drive ``supersteps`` dispatches back-to-back, then sync the
        trained state into the solver. Returns the final chunk's metrics
        (host numpy — the ONE deliberate readback, at the very end)."""
        for _ in range(int(supersteps)):
            self.superstep()
        self.sync_solver()
        return {kk: np.asarray(vv) for kk, vv in
                (self.last_metrics or {}).items()}

    @property
    def dstate(self) -> DeviceReplayState:
        """The live ring twin (it rides the donated carry)."""
        return self._carry[0]

    @property
    def env_steps(self) -> int:
        return self.supersteps_run * self.ticks * self.num_envs

    @property
    def grad_steps(self) -> int:
        return self.supersteps_run * self.chain

    def sync_solver(self) -> TrainState:
        """Fold the planes back into ``solver.state`` (and the ring twin
        back into the replay object) — the seam that keeps Anakin a mode:
        checkpoints, ``q_values``, ``get_weights`` all read the solver."""
        (ds, _env, _buf, _ak, _cur, _siz, pt, m, v, cnt, gstep) = \
            self._carry
        state = self.solver.state
        adam_state, rebuild = _locate_adam_state(state.opt_state)
        params, target = plane_to_param_trees(
            self._meta, pt, state.params, state.target_params)
        new_opt = rebuild(adam_state._replace(
            count=cnt, mu=plane_to_tree(self._meta, m, adam_state.mu),
            nu=plane_to_tree(self._meta, v, adam_state.nu)))
        self.solver.state = TrainState(params, target, new_opt, gstep)
        self.replay.dstate = ds
        return self.solver.state


def run_anakin(cfg: Config, supersteps: int) -> dict[str, Any]:
    """Entry point: build a runner, train, return final metrics (with the
    episode-reward scalar folded in). The distributed path's
    ``train_distributed`` stays untouched — Anakin is selected explicitly
    (``cfg.actors.anakin_envs > 0``), not inferred."""
    runner = AnakinRunner(cfg)
    out = runner.run(supersteps)
    out["act_reward"] = float(np.asarray(runner.last_act_reward))
    return out
