"""Multi-host learner support (SURVEY.md §5.8, §2.2 "Multi-host DP" [M]).

The reference scales workers across nodes with Spark (actor + gradient
parallelism, parameter-server plane [M][P]); the TPU-native equivalent is
**multi-controller JAX**: every learner process runs the same program,
``jax.distributed.initialize`` connects them into one runtime, the device
mesh spans all processes, and the existing ``shard_map`` + ``lax.pmean``
train step works unchanged — XLA routes the gradient allreduce over ICI
within a slice and DCN across hosts (config 5's "v4-32 multi-host" path).
No gradient code changes between 1 host and N hosts; that is the point.

What does change is *data placement*: in multi-controller mode a process
can only hand JAX the rows that live on its own devices. The helpers here
are that seam:

- ``initialize_multihost(cfg)`` — one-call bring-up from ``MeshConfig``.
  On the ``cpu`` test backend it pins the platform, splits
  ``num_fake_devices`` virtual devices evenly across processes, and selects
  the gloo cross-process collective implementation (the reference's
  ``local[N]``-style Spark test mode, rebuilt — SURVEY §4).
- ``global_batch(sharding, batch)`` — assemble the global sharded batch
  from each process's local rows (``jax.make_array_from_process_local_data``).
- ``put_replicated(tree, sharding)`` — replicate host values across every
  process's devices (TrainState init / weight installs).
- ``local_rows(arr)`` — read back this process's rows of a batch-sharded
  result (per-sample |TD| for PER write-back into the local replay shard).

Process topology for config 5: each learner process hosts its own replay
shard fed by its own slice of the actor fleet (per-host replay shards,
SURVEY §7.3 item 6 — sampling is dedup-free because shards never overlap);
the per-process sample feeds ``global_batch``; metrics out-specs are
replicated so every process can read them without extra collectives.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from distributed_deep_q_tpu.config import MeshConfig


def initialize_multihost(cfg: MeshConfig) -> None:
    """Connect this process to the multi-controller runtime (idempotent).

    Must run before any JAX backend initialization in the process. With
    ``num_processes <= 1`` this is a no-op, so single-host entry points can
    call it unconditionally.
    """
    if cfg.num_processes <= 1:
        return
    # NOTE: do not probe jax.process_count() here — it initializes the
    # backend, which forbids the device-count config updates below. The
    # distributed client handle is the init-free "already connected?" signal.
    from jax._src import distributed as _dist
    if _dist.global_state.client is not None:
        return  # already connected
    if cfg.backend == "cpu":
        if cfg.num_fake_devices % cfg.num_processes:
            raise ValueError(
                f"num_fake_devices={cfg.num_fake_devices} must divide evenly "
                f"across num_processes={cfg.num_processes}")
        # same pre-init pattern as parallel.mesh._cpu_devices: override the
        # container's platform latch, then size this process's local slice
        jax.config.update("jax_platforms", "cpu")
        from distributed_deep_q_tpu.compat import set_cpu_device_count
        set_cpu_device_count(cfg.num_fake_devices // cfg.num_processes,
                             exact=True)
        # cross-process collectives on the CPU backend go through gloo
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    kwargs: dict[str, Any] = {}
    if cfg.coordinator:
        kwargs = dict(coordinator_address=cfg.coordinator,
                      num_processes=cfg.num_processes,
                      process_id=cfg.process_id)
    # on TPU pods initialize() auto-detects everything from the metadata
    # server when no coordinator is given
    jax.distributed.initialize(**kwargs)


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def put_replicated(tree: Any, sharding) -> Any:
    """Place a host pytree onto every device of a (possibly multi-host)
    mesh. ``sharding`` is either ONE sharding applied to every leaf (the
    replicated TrainState path) or a matching pytree of per-leaf
    shardings (partition-rule placement, ``parallel.mesh.tree_shardings``
    — ISSUE 10's model-axis hook). Single-process: plain ``device_put``.
    Multi-process: every process holds the full value, so the
    process-local data IS the global array."""
    if not is_multiprocess():
        return jax.device_put(tree, sharding)

    def put(x, s):
        x = np.asarray(x)
        return jax.make_array_from_process_local_data(
            s, x, global_shape=x.shape)

    if isinstance(sharding, jax.sharding.Sharding):
        return jax.tree.map(lambda x: put(x, sharding), tree)
    return jax.tree.map(put, tree, sharding)


def global_batch(sharding, batch: dict[str, Any]) -> dict[str, Any]:
    """Assemble the global batch from this process's local rows.

    Each process passes its own ``global_B / process_count`` rows (its
    replay shard's sample); the returned dict holds global jax.Arrays
    sharded over the batch axis, ready for the sharded train step.
    Single-process mode passes the batch through untouched (jit shards
    host arrays itself).
    """
    if not is_multiprocess():
        return batch
    n = jax.process_count()

    def put(x):
        x = np.asarray(x)
        return jax.make_array_from_process_local_data(
            sharding, x, global_shape=(x.shape[0] * n,) + x.shape[1:])

    return {k: put(v) for k, v in batch.items()}


def all_processes_ready(local_ready: bool) -> bool:
    """AND-reduce a host-side readiness flag across processes.

    Used to open the learn gate simultaneously on every process (each host
    fills its own replay shard at its own pace; the sharded train step is a
    collective, so no process may enter it early). This is itself a
    collective — every process must call it at the same loop point.
    Single-process: identity.
    """
    if not is_multiprocess():
        return bool(local_ready)
    from jax.experimental import multihost_utils
    flags = multihost_utils.process_allgather(np.asarray([bool(local_ready)]))
    return bool(np.all(flags))


def global_max_int(value: int) -> int:
    """MAX-reduce a host-side integer across processes. Collective —
    every process must call it at the same loop point. Used by the
    multi-host fused replay to agree on a uniform flush-round count
    before the lockstep flush dispatches (each host's staged backlog
    differs; the flush program is a global-array computation every
    process must enter the same number of times). Single-process:
    identity."""
    if not is_multiprocess():
        return int(value)
    from jax.experimental import multihost_utils
    vals = multihost_utils.process_allgather(np.asarray([int(value)]))
    return int(np.max(vals))


def local_rows(arr: jax.Array) -> np.ndarray:
    """This process's rows of a batch-axis-sharded result, in shard order
    (e.g. per-sample |TD| destined for the local replay shard's PER
    write-back). Works in single-process mode too (returns all rows)."""
    shards = sorted(arr.addressable_shards, key=lambda s: s.index[0].start or 0)
    return np.concatenate([np.asarray(s.data) for s in shards], axis=0)
