"""Recurrent (R2D2) sequence learner — config 5 [M].

Same synchronous-DP shape as ``parallel/learner.py`` (shard_map over the
``dp`` mesh axis, ``lax.pmean`` gradient allreduce over ICI, replicated
on-device target refresh), with the R2D2 sequence step inside one XLA
program:

1. **Burn-in**: the LSTM runs over the first ``burn_in`` steps from the
   *stored* carry to refresh recurrent state; ``stop_gradient`` on the
   resulting carry keeps burn-in out of the backward pass (SURVEY §7.3
   item 3). The unroll is a flax ``nn.RNN`` = lifted ``lax.scan`` — one
   fused scan body, compiler-friendly, no Python unrolling.
2. **Train window**: online and target nets unroll over the remaining
   ``T+1`` observations; per-step Double-DQN targets with R2D2 invertible
   value rescaling (``ops/losses.sequence_bellman_targets``).
3. **Masked loss + priority**: ``sequence_dqn_loss`` masks padding and
   burn-in, and returns the mixed max/mean |TD| per-sequence priority for
   PER write-back.

Batch sequences are sharded over ``dp`` on the batch axis — the scope
decision recorded in SURVEY §5.7: sequence *length* stays ≤ O(100) steps so
sequence-axis parallelism (ring attention / Ulysses-style CP) is
deliberately not applicable; scale comes from sharding the batch of
sequences.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from distributed_deep_q_tpu.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_deep_q_tpu import learning
from distributed_deep_q_tpu.config import ReplayConfig, TrainConfig
from distributed_deep_q_tpu.models.qnet import (
    r2d2_burn_carry, r2d2_param_split, r2d2_recur, stacked_r2d2_features)
from distributed_deep_q_tpu.ops.losses import (
    sequence_bellman_targets, sequence_dqn_loss)
from distributed_deep_q_tpu.parallel.learner import (
    TrainState, clip_grads, fused_adam_target_step, make_optimizer,
    refresh_target)
from distributed_deep_q_tpu.parallel.mesh import AXIS_DP
from distributed_deep_q_tpu.parallel.multihost import (
    global_batch, put_replicated)


class SequenceLearner:
    """Owns the sharded R2D2 train step for recurrent Q-nets."""

    def __init__(self, module, cfg: TrainConfig, replay_cfg: ReplayConfig,
                 mesh):
        self.module = module
        self.cfg = cfg
        self.burn_in = int(replay_cfg.burn_in)
        self.mesh = mesh
        self.opt = make_optimizer(cfg)
        self._replicated = NamedSharding(mesh, P())
        self._batch_sharding = NamedSharding(mesh, P(AXIS_DP))
        self._train_step = self._build_train_step()
        # device-sequence-ring steps, keyed on ring geometry
        self._ring_steps: dict[tuple, Any] = {}
        # fused chained sequence steps, keyed on (spec, chain)
        self._fused_steps: dict[tuple, Any] = {}

    def init_state(self, params: Any) -> TrainState:
        state = TrainState(
            params=params,
            target_params=jax.tree.map(jnp.copy, params),
            opt_state=self.opt.init(params),
            step=jnp.zeros((), jnp.int32),
        )
        return put_replicated(state, self._replicated)

    def _step_core(self, state: TrainState, batch: dict[str, jax.Array]):
        """Burn-in + train-window unroll + masked loss + optimizer — the
        per-shard R2D2 step body, shared by the host-batch program and the
        device-sequence-ring train program."""
        cfg, burn = self.cfg, self.burn_in
        module, opt = self.module, self.opt

        def apply_seq(params, obs, carry):
            return module.apply({"params": params}, obs, carry)

        def step_fn(state: TrainState, batch: dict[str, jax.Array]):
            obs = batch["obs"]                    # [B, T_total+1, ...]
            carry0 = (batch["init_c"], batch["init_h"])
            # static gate, same policy as Learner._step_core: the stacked
            # time-batched torso wins whenever the step is op-count-bound
            use_stacked = (cfg.stack_forwards == "on"
                           or (cfg.stack_forwards == "auto"
                               and obs.shape[0] <= 128))

            def loss_fn(params):
                if use_stacked:
                    # Op-count surgery (PERF.md §4): the conv torso runs
                    # ONCE, time-batched over ALL [B·(T_total+1)] frames —
                    # burn-in included — for θ AND θ⁻ together (stacked
                    # weights, models/qnet.py); only the LSTM recurs. The
                    # scheduled conv count is therefore independent of
                    # both the sequence length and the number of nets,
                    # where the module-apply path pays four separate conv
                    # chains (on/target × burn/window). Gradients still
                    # cut at the burn-in seam: the burn features only
                    # reach the loss through the stop-gradded carry.
                    feats = stacked_r2d2_features(
                        module, params, state.target_params, obs)
                    _, l_on, h_on = r2d2_param_split(params)
                    _, l_tg, h_tg = r2d2_param_split(state.target_params)
                    f_on, f_tg = feats[0], feats[1]
                    if burn > 0:
                        carry_on = lax.stop_gradient(r2d2_burn_carry(
                            module, l_on, f_on[:, :burn], carry0))
                        carry_tg = r2d2_burn_carry(
                            module, l_tg, f_tg[:, :burn], carry0)
                    else:
                        carry_on = carry_tg = carry0
                    q_all, _ = r2d2_recur(module, l_on, h_on,
                                          f_on[:, burn:], carry_on)
                    q_tgt_all, _ = r2d2_recur(module, l_tg, h_tg,
                                              f_tg[:, burn:], carry_tg)
                else:
                    # burn-in from the stored carry; grads cut at the seam
                    if burn > 0:
                        _, carry_on = apply_seq(params, obs[:, :burn],
                                                carry0)
                        carry_on = lax.stop_gradient(carry_on)
                        _, carry_tg = apply_seq(state.target_params,
                                                obs[:, :burn], carry0)
                    else:
                        carry_on = carry_tg = carry0

                    # train window: T+1 obs → q for steps and bootstraps
                    q_all, _ = apply_seq(params, obs[:, burn:], carry_on)
                    q_tgt_all, _ = apply_seq(state.target_params,
                                             obs[:, burn:], carry_tg)
                q = q_all[:, :-1]                           # [B, T, A]
                q_next_online = lax.stop_gradient(q_all[:, 1:])
                q_next_target = q_tgt_all[:, 1:]

                targets = sequence_bellman_targets(
                    batch["reward"][:, burn:], batch["discount"][:, burn:],
                    q_next_target, q_next_online,
                    double=cfg.double_dqn, rescale=cfg.value_rescale)
                loss, priority = sequence_dqn_loss(
                    q, batch["action"][:, burn:], targets,
                    batch["mask"][:, burn:], batch["weight"],
                    cfg.huber_delta, eta=cfg.priority_eta)
                return loss, (priority, q)

            (loss, (priority, q)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)

            grads = lax.pmean(grads, AXIS_DP)
            loss = lax.pmean(loss, AXIS_DP)
            q_mean = lax.pmean(jnp.mean(q), AXIS_DP)

            gnorm = optax.global_norm(grads)
            step = state.step + 1
            if cfg.optimizer == "adam":
                # clip + Adam + target refresh in the one fused tree pass
                # (the lax.cond refresh scheduled a whole-tree copy per
                # step — see fused_adam_target_step)
                opt_state, params, target_params = fused_adam_target_step(
                    cfg, grads, state.opt_state, state.params,
                    state.target_params, gnorm, step)
            else:
                grads, gnorm = clip_grads(cfg, grads, gnorm)
                updates, opt_state = opt.update(grads, state.opt_state,
                                                state.params)
                params = optax.apply_updates(state.params, updates)
                target_params = refresh_target(cfg, params,
                                               state.target_params, step)
            new_state = TrainState(params, target_params, opt_state, step)
            metrics = {
                "loss": loss,
                "q_mean": q_mean,
                "grad_norm": gnorm,
            }
            if cfg.learn_metrics:
                # learning-dynamics plane (learning.py): the recurrent
                # step's Q extreme, reduced here so the fused chain's
                # plane sees a replicated scalar (lm_finalize's pmax is
                # then idempotent). Static gate — off traces nothing.
                metrics["q_max"] = lax.pmax(jnp.max(q), AXIS_DP)
            return new_state, metrics, priority

        return step_fn(state, batch)

    def _build_train_step(self):
        sharded = shard_map(
            lambda state, batch: self._step_core(state, batch),
            mesh=self.mesh,
            in_specs=(P(), P(AXIS_DP)),
            out_specs=(P(), P(), P(AXIS_DP)),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=0)

    def _build_ring_step(self, geom: tuple):
        """Per-step R2D2 ring path (host-sampled indices): the SAMPLE
        program DMA-copies each drawn sequence's contiguous W-row block
        out of the flat padded ring (``ops/ring_gather.py`` — one DMA per
        sequence, no gather lowering); the TRAIN program slices the
        stacked observations out of the blocks (static slices,
        ``compose_sequence_block``) and runs the recurrent step. Pixels
        never cross the host boundary per step — only KB-scale metadata
        does. The CHAINED path (``_build_fused_steps``) is the
        throughput mode; this one serves host-tree PER and the
        RPC-driven per-step loops."""
        (seq_len, stack, frame_shape, W, rowb, row_len, per_shard,
         interpret) = geom
        from distributed_deep_q_tpu.ops.ring_gather import gather_windows
        from distributed_deep_q_tpu.replay.device_sequence import (
            compose_sequence_block)

        S = P(AXIS_DP)
        rowp = rowb // 4

        def sample_fn(ring, seq_local):
            win = gather_windows(seq_local * W, ring, n=per_shard, w=W,
                                 rowb=rowb, interpret=interpret)
            return win.reshape(per_shard, W, rowp)

        sample = jax.jit(shard_map(
            sample_fn, mesh=self.mesh, in_specs=(S, S), out_specs=S,
            check_vma=False))

        def train_fn(state: TrainState, block, batch):
            h, w = frame_shape
            batch = dict(batch)
            obs = compose_sequence_block(block, batch["mask"], seq_len,
                                         stack, row_len)
            obs = obs.reshape(obs.shape[:3] + (h, w))
            batch["obs"] = jnp.moveaxis(obs, 2, -1)  # [b, T+1, H, W, S]
            return self._step_core(state, batch)

        # donate the state tree: params/target/opt alias their updated
        # outputs, so the optimizer writes in place. The pixel block has
        # no same-shaped output to alias — donating it would be a no-op.
        train = jax.jit(shard_map(
            train_fn, mesh=self.mesh,
            in_specs=(P(), S, S),
            out_specs=(P(), P(), S),
            check_vma=False), donate_argnums=0)
        return sample, train

    def train_step_from_ring(self, state: TrainState, replay, batch):
        """One DP step composing sequence pixels from the HBM ring; returns
        (state, metrics, per-sequence priority [B])."""
        b = len(batch["seq_local"])
        geom = (replay.seq_len, replay.stack, tuple(replay.frame_shape),
                replay.W, replay.rowb, replay._row_len,
                b // replay.num_shards, replay._interpret)
        if geom not in self._ring_steps:
            self._ring_steps[geom] = self._build_ring_step(geom)
        sample, train = self._ring_steps[geom]
        rows = sample(replay.ring, np.asarray(batch["seq_local"], np.int32))
        meta = {k: v for k, v in batch.items()
                if k not in ("seq_local", "n_valid")}
        return train(state, rows, meta)

    def _build_fused_steps(self, spec: tuple, chain: int):
        """Chained fused sequence steps — the transition path's two-program
        structure (``Learner._build_device_per_step``) on the sequence
        ring: the SAMPLE program draws all ``chain`` sequence batches
        against chunk-start priorities (inverse-CDF over the device
        priority row), row-gathers their metadata, and DMA-copies each
        sequence's contiguous W-row pixel block; the TRAIN program scans
        the ``chain`` recurrent steps with same-step per-sequence
        priority scatters. Per chunk the host ships per-shard sizes, βs,
        and keys — nothing reads back. Per-step dispatch caps at ~133/s
        on this runtime (PERF §2, measured 50.6/s for the r4 sequence
        path); chaining is what lifts the R2D2 device path past it."""
        (caps_local, seq_len, stack, W, rowb, row_len, frame_shape,
         per_shard, alpha, eps, num_shards, interpret) = spec
        from distributed_deep_q_tpu.ops.ring_gather import gather_windows
        from distributed_deep_q_tpu.replay.device_per import (
            build_cdf, draw_from_cdf, scatter_priorities,
            stratified_is_weights)
        from distributed_deep_q_tpu.replay.device_sequence import (
            compose_sequence_block)

        S = P(AXIS_DP)
        SK = P(None, AXIS_DP)
        SK3 = P(None, AXIS_DP, None)
        SWIN = P(None, AXIS_DP, None, None)
        rowp = rowb // 4
        n_win = chain * per_shard

        def sample_fn(keys, ring, dmeta, sizes, betas):
            filled = (jnp.arange(caps_local) < sizes[0]).astype(
                jnp.float32)
            pm = dmeta["prio"] * filled
            cdf, mass = build_cdf(pm)
            n_glob = lax.psum(jnp.sum(filled), AXIS_DP)
            idx, p = jax.vmap(
                lambda k: draw_from_cdf(k, cdf, pm, mass, per_shard))(
                keys[0])                               # [chain, b]
            flat = idx.reshape(-1)
            metas = {key: dmeta[key][flat].reshape(
                (chain, per_shard) + dmeta[key].shape[1:])
                for key in ("action", "reward", "discount", "mask",
                            "init_c", "init_h")}
            metas["weight"] = stratified_is_weights(p, mass, n_glob,
                                                    betas, num_shards)
            win = gather_windows(flat * W, ring, n=n_win, w=W, rowb=rowb,
                                 interpret=interpret)
            idx = jnp.where(mass > 0, idx, caps_local)
            return (metas, win.reshape(chain, per_shard, W, rowp),
                    idx.astype(jnp.int32))

        meta_spec = {"action": SK3, "reward": SK3, "discount": SK3,
                     "mask": SK3, "init_c": SK3, "init_h": SK3,
                     "weight": SK}
        dmeta_spec = {k: S for k in ("action", "reward", "discount",
                                     "mask", "init_c", "init_h", "prio")}
        sample = jax.jit(shard_map(
            sample_fn, mesh=self.mesh,
            in_specs=(S, S, dmeta_spec, S, P()),
            out_specs=(meta_spec, SWIN, SK),
            check_vma=False))

        def train_fn(state: TrainState, metas, win, idxs, prio, maxp):
            h, wd = frame_shape
            lm = bool(self.cfg.learn_metrics)  # static trace-time gate

            def body(carry, xs):
                if lm:
                    state, prio, maxp, lmp = carry
                else:
                    state, prio, maxp = carry
                batch, block, idx = xs
                batch = dict(batch)
                obs = compose_sequence_block(block, batch["mask"],
                                             seq_len, stack, row_len)
                obs = obs.reshape(obs.shape[:3] + (h, wd))
                batch["obs"] = jnp.moveaxis(obs, 2, -1)
                state, metrics, priority = self._step_core(state, batch)
                prio, maxp = scatter_priorities(prio, maxp, idx, priority,
                                                alpha, eps)
                if lm:
                    # per-sequence mixed max/mean |TD| (the PER priority
                    # statistic of record on the R2D2 path) feeds the TD
                    # histogram; loss/q_mean/gnorm arrive pmean'd from
                    # _step_core, q_max already pmax'd (idempotent under
                    # lm_finalize's pmax)
                    lmp = learning.lm_update(
                        lmp, cfg=self.cfg, td_abs=priority,
                        weight=batch["weight"], loss=metrics["loss"],
                        q=metrics["q_max"], q_mean=metrics["q_mean"],
                        gnorm=metrics["grad_norm"], step=state.step,
                        alpha=alpha, eps=eps)
                    return (state, prio, maxp, lmp), metrics
                return (state, prio, maxp), metrics

            if lm:
                (state, prio, maxp, lmp), metrics = lax.scan(
                    body, (state, prio, maxp, learning.lm_init()),
                    (metas, win, idxs))
                metrics = dict(metrics)
                metrics["learn_plane"] = learning.lm_finalize(lmp, AXIS_DP)
            else:
                (state, prio, maxp), metrics = lax.scan(
                    body, (state, prio, maxp), (metas, win, idxs))
            return state, prio, maxp, metrics

        # donate every input with an updated output to alias (transition
        # path's discipline): the state tree (0) and prio/maxp (4, 5) are
        # rewritten in place instead of through defensive copies. metas/
        # win/idxs have no same-shaped output, so donating them is a no-op
        # (XLA donation is strictly output aliasing).
        train = jax.jit(shard_map(
            train_fn, mesh=self.mesh,
            in_specs=(P(), meta_spec, SWIN, SK, S, P()),
            out_specs=(P(), S, P(), P()),
            check_vma=False), donate_argnums=(0, 4, 5))
        return sample, train

    def train_steps_fused(self, state: TrainState, replay, batch_size: int,
                          sizes, betas: np.ndarray, keys: np.ndarray):
        """``len(betas)`` fused sequence steps in one two-program dispatch.
        Returns (state, new prio, new maxp, metrics stacked [chain])."""
        chain = len(betas)
        spec = (replay.caps_local, replay.seq_len, replay.stack, replay.W,
                replay.rowb, replay._row_len, tuple(replay.frame_shape),
                batch_size // replay.num_shards,
                replay.alpha, replay.eps, replay.num_shards,
                replay._interpret)
        cache_key = (spec, chain)
        if cache_key not in self._fused_steps:
            self._fused_steps[cache_key] = self._build_fused_steps(
                spec, chain)
        sample, train = self._fused_steps[cache_key]

        def feed(x, dtype=None):
            # multi-host global arrays pass through untouched
            return x if isinstance(x, jax.Array) else np.asarray(x, dtype)

        metas, win, idx = sample(keys, replay.ring, replay.dmeta,
                                 feed(sizes), feed(betas, np.float32))
        return train(state, metas, win, idx, replay.dmeta["prio"],
                     replay.dmaxp)

    def train_step(self, state: TrainState, batch: dict[str, Any]):
        """One synchronous DP step over a [B, T_total(+1)] sequence batch;
        returns (state, metrics, per-sequence priority [B]). In multi-host
        mode each process passes its local B/process_count sequences (same
        contract as ``Learner.train_step``)."""
        return self._train_step(state, global_batch(self._batch_sharding,
                                                    batch))


class SequenceSolver:
    """Reference ``Solver`` surface for the recurrent pipeline.

    Mirrors ``solver.Solver`` (train_step / q_values / act / weight IO [M])
    with recurrent state threading for the actor path.
    """

    def __init__(self, config, obs_dim: int = 4, backend: str | None = None):
        import dataclasses

        from distributed_deep_q_tpu.models.qnet import (
            QNet, build_qnet, init_params)
        from distributed_deep_q_tpu.parallel.mesh import make_mesh
        from distributed_deep_q_tpu.solver import _strip_host_keys

        assert config.net.kind == "r2d2", "SequenceSolver is for r2d2 nets"
        if backend is not None:
            config = dataclasses.replace(
                config, mesh=dataclasses.replace(config.mesh, backend=backend))
        self.config = config
        self.backend = config.mesh.backend
        self.mesh = make_mesh(config.mesh)
        self.module = build_qnet(config.net)
        self.learner = SequenceLearner(self.module, config.train,
                                       config.replay, self.mesh)
        params = init_params(self.module, config.net, config.train.seed,
                             obs_dim)
        self.state: TrainState = self.learner.init_state(params)
        self._treedef = jax.tree_util.tree_structure(params)
        self._strip = _strip_host_keys
        self._fwd = jax.jit(
            lambda p, o, c: self.module.apply({"params": p}, o, c))
        # fused chained-path key bookkeeping (Solver's scheme)
        self._fused_key_base: int | None = None
        self._fused_steps_issued = 0

    @property
    def step(self) -> int:
        return int(self.state.step)

    def train_step(self, batch: dict[str, Any]) -> dict[str, Any]:
        self.state, metrics, priority = self.learner.train_step(
            self.state, self._strip(batch))
        out: dict[str, Any] = dict(metrics)
        out["td_abs"] = priority  # per-sequence priority for PER write-back
        if "index" in batch:
            out["index"] = batch["index"]
        return out

    def train_step_from_ring(self, replay, batch: dict[str, Any],
                             ) -> dict[str, Any]:
        """One R2D2 step with pixels composed from the device-resident
        sequence ring (``DeviceSequenceReplay``): ``batch`` carries only
        sequence metadata + shard-local slot indices."""
        self.state, metrics, priority = self.learner.train_step_from_ring(
            self.state, replay, self._strip(batch))
        out: dict[str, Any] = dict(metrics)
        out["td_abs"] = priority
        if "index" in batch:
            out["index"] = batch["index"]
        return out

    def train_steps_device_per(self, replay,
                               chain: int | None = None) -> dict[str, Any]:
        """``chain`` fused sequence steps in ONE two-program dispatch
        (sampling, metadata, pixels, and per-sequence priority updates all
        on device — ``SequenceLearner._build_fused_steps``). Same protocol
        as ``Solver.train_steps_device_per`` so ``FusedStepStream`` drives
        either. Returns metrics stacked [chain]."""
        from distributed_deep_q_tpu.solver import next_fused_keys

        chain = chain or max(int(self.config.replay.fused_chain), 1)
        if replay.pending_rows() or replay.defer_flush:
            # multi-host the flush is a lockstep collective with an
            # agreed round count — every process calls it here
            replay.flush()
        sizes = replay.device_inputs()
        betas = replay.next_betas(chain)
        keys = next_fused_keys(self, replay.num_shards, chain)
        if replay._pc > 1:
            keys = replay.to_global(
                np.ascontiguousarray(keys[replay.local_shards]))
            sizes = replay.to_global(np.asarray(sizes))
            betas = replay.to_replicated(np.asarray(betas, np.float32))
        self.state, prio, maxp, metrics = self.learner.train_steps_fused(
            self.state, replay, self.config.replay.batch_size, sizes,
            betas, keys)
        replay.dmeta = dict(replay.dmeta)
        replay.dmeta["prio"] = prio
        replay.dmaxp = maxp
        return dict(metrics)

    # -- recurrent actor path ----------------------------------------------

    def initial_state(self, batch_size: int = 1):
        from distributed_deep_q_tpu.models.qnet import R2d2QNet
        return R2d2QNet(self.config.net.num_actions,
                        self.config.net.lstm_size).initial_state(batch_size)

    def q_values(self, obs: np.ndarray, carry):
        """obs [B, ...] single step → (q [B, A], next carry)."""
        q, carry = self._fwd(self.state.params, np.asarray(obs)[:, None],
                             carry)
        return np.asarray(q[:, 0]), carry

    def act(self, obs: np.ndarray, carry, epsilon: float,
            rng: np.random.Generator):
        """ε-greedy with recurrent state; returns (action, next carry).

        The carry ALWAYS advances (even on random actions) so stored actor
        state matches what the policy network saw — required for the
        stored-state burn-in strategy to be meaningful."""
        q, carry = self.q_values(obs[None], carry)
        if rng.random() < epsilon:
            return int(rng.integers(self.config.net.num_actions)), carry
        return int(np.argmax(q[0])), carry

    # -- weight IO ----------------------------------------------------------

    def get_weights(self) -> list[np.ndarray]:
        return [np.asarray(x)
                for x in jax.tree_util.tree_leaves(self.state.params)]

    def update(self, weights: list[np.ndarray]) -> None:
        params = jax.tree_util.tree_unflatten(self._treedef, list(weights))
        params = jax.device_put(params, self.learner._replicated)
        self.state = self.state.replace(params=params)

    set_weights = update
