"""Device-mesh construction — the rebuilt ``--backend`` switch (SURVEY §7.1).

The reference selects its compute backend with a ``--backend`` flag on the
``Solver`` [M]. Here a backend is (platform, device mesh): ``tpu`` uses the
accelerator platform JAX initialized; ``cpu`` forces the host platform with
N virtual devices (``jax_num_cpu_devices``) — the dummy/test backend that
lets the full multi-device psum learner run anywhere (SURVEY §4).

Mesh axes: ``dp`` (data parallel — batch sharded, grads psum'ed over ICI)
and ``model`` (tensor-parallel hook; size 1 for every reference config —
SURVEY §2.2 records TP/PP as deliberately out of scope).
"""

from __future__ import annotations

import re

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_deep_q_tpu.config import MeshConfig

AXIS_DP = "dp"
AXIS_MODEL = "model"

# -- declarative partition rules (ISSUE 10; SNIPPETS.md [2][3] idiom) ------
#
# regex → PartitionSpec, matched with ``re.search`` against the
# '/'-joined path of every leaf in a pytree. First match wins; scalars
# short-circuit to replicated; the final catch-all means resolution
# never fails. Today every config runs ``model=1`` so all of these
# BEHAVE replicated — the rules are the declarative seam that lets a
# torso grow past replicated without touching the learner: widen the
# net, raise ``mesh.model``, and the same table shards it.
#
# Matching the leaf PATH (not just the leaf name) means the rules
# resolve identically for ``params/Conv_0/kernel`` and its optimizer
# mirrors ``opt_state/.../mu/Conv_0/kernel`` — moments inherit their
# parameter's spec for free.
DEFAULT_PARTITION_RULES: tuple[tuple[str, P], ...] = (
    # torso conv kernels [H, W, Cin, Cout]: shard output features
    (r"torso/conv\d+/kernel$", P(None, None, None, AXIS_MODEL)),
    # torso dense kernels [in, out]: shard output features
    (r"torso/fc\d+/kernel$", P(None, AXIS_MODEL)),
    # per-output-feature vectors ride with their kernel's output shard
    (r"torso/(conv|fc)\d+/bias$", P(AXIS_MODEL)),
    # heads (q/value/advantage — num_actions wide, tiny), the LSTM, and
    # every scalar stay replicated
    (r".*", P()),
)


def _path_name(path) -> str:
    parts = []
    for k in path:
        parts.append(str(getattr(k, "key", getattr(k, "idx", k))))
    return "/".join(parts)


def match_partition_rules(rules, tree):
    """Resolve a pytree of ``PartitionSpec``s from ``(regex, spec)`` rules.

    Scalar leaves are always replicated (a spec can't partition rank 0);
    everything else takes the first rule whose regex ``re.search``-matches
    its '/'-joined tree path. Raises on an unmatched leaf — add a
    catch-all ``(".*", P())`` tail if silence is wanted (the default
    table has one).
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    specs = []
    for path, leaf in flat:
        name = _path_name(path)
        if np.ndim(leaf) == 0:
            specs.append(P())
            continue
        for pat, spec in rules:
            if re.search(pat, name):
                specs.append(spec)
                break
        else:
            raise ValueError(f"no partition rule matches {name!r}")
    return jax.tree_util.tree_unflatten(treedef, specs)


def tree_shardings(mesh: Mesh, tree, rules=None):
    """Pytree of ``NamedSharding``s for ``tree`` under the rule table —
    the placement argument for ``put_replicated`` / ``device_put`` when
    the model axis is real (>1). Specs that name an axis of size 1
    still produce valid shardings (they behave replicated)."""
    specs = match_partition_rules(rules or DEFAULT_PARTITION_RULES, tree)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def _cpu_devices(n: int) -> list[jax.Device]:
    """Force-create n virtual CPU devices (works pre- or post-backend-init).

    ``n`` counts GLOBAL devices. In multi-controller mode (multihost
    learner, SURVEY §5.8) the per-process device count was already fixed by
    ``initialize_multihost`` — raising it here would inflate the global
    device count — so the override only runs when no distributed client is
    connected.
    """
    from jax._src import distributed as _dist
    if _dist.global_state.client is None:
        try:
            # pre-init: steer platform selection (overrides the container's
            # sitecustomize JAX_PLATFORMS latch). Only ever *raise* the device
            # count — a small mesh built first must not cap later larger ones.
            jax.config.update("jax_platforms", "cpu")
            from distributed_deep_q_tpu.compat import set_cpu_device_count
            set_cpu_device_count(n)
        except Exception:
            pass
    devs = jax.devices("cpu")
    if len(devs) < n:
        raise RuntimeError(
            f"backend=cpu wants {n} virtual devices but only {len(devs)} exist; "
            "set mesh.num_fake_devices before any JAX backend initialization")
    return devs[:n]


def mesh_devices(cfg: MeshConfig) -> list[jax.Device]:
    if cfg.backend == "cpu":
        n = cfg.num_fake_devices if cfg.dp == 0 else cfg.dp * max(cfg.model, 1)
        return _cpu_devices(n)
    if cfg.backend != "tpu":
        raise ValueError(f"unknown backend {cfg.backend!r} (want tpu|cpu)")
    return jax.devices()


def make_mesh(cfg: MeshConfig) -> Mesh:
    devs = mesh_devices(cfg)
    model = max(cfg.model, 1)
    dp = cfg.dp if cfg.dp > 0 else len(devs) // model
    devs = devs[: dp * model]
    arr = np.asarray(devs).reshape(dp, model)
    return Mesh(arr, (AXIS_DP, AXIS_MODEL))
