"""Device-mesh construction — the rebuilt ``--backend`` switch (SURVEY §7.1).

The reference selects its compute backend with a ``--backend`` flag on the
``Solver`` [M]. Here a backend is (platform, device mesh): ``tpu`` uses the
accelerator platform JAX initialized; ``cpu`` forces the host platform with
N virtual devices (``jax_num_cpu_devices``) — the dummy/test backend that
lets the full multi-device psum learner run anywhere (SURVEY §4).

Mesh axes: ``dp`` (data parallel — batch sharded, grads psum'ed over ICI)
and ``model`` (tensor-parallel hook; size 1 for every reference config —
SURVEY §2.2 records TP/PP as deliberately out of scope).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from distributed_deep_q_tpu.config import MeshConfig

AXIS_DP = "dp"
AXIS_MODEL = "model"


def _cpu_devices(n: int) -> list[jax.Device]:
    """Force-create n virtual CPU devices (works pre- or post-backend-init).

    ``n`` counts GLOBAL devices. In multi-controller mode (multihost
    learner, SURVEY §5.8) the per-process device count was already fixed by
    ``initialize_multihost`` — raising it here would inflate the global
    device count — so the override only runs when no distributed client is
    connected.
    """
    from jax._src import distributed as _dist
    if _dist.global_state.client is None:
        try:
            # pre-init: steer platform selection (overrides the container's
            # sitecustomize JAX_PLATFORMS latch). Only ever *raise* the device
            # count — a small mesh built first must not cap later larger ones.
            jax.config.update("jax_platforms", "cpu")
            from distributed_deep_q_tpu.compat import set_cpu_device_count
            set_cpu_device_count(n)
        except Exception:
            pass
    devs = jax.devices("cpu")
    if len(devs) < n:
        raise RuntimeError(
            f"backend=cpu wants {n} virtual devices but only {len(devs)} exist; "
            "set mesh.num_fake_devices before any JAX backend initialization")
    return devs[:n]


def mesh_devices(cfg: MeshConfig) -> list[jax.Device]:
    if cfg.backend == "cpu":
        n = cfg.num_fake_devices if cfg.dp == 0 else cfg.dp * max(cfg.model, 1)
        return _cpu_devices(n)
    if cfg.backend != "tpu":
        raise ValueError(f"unknown backend {cfg.backend!r} (want tpu|cpu)")
    return jax.devices()


def make_mesh(cfg: MeshConfig) -> Mesh:
    devs = mesh_devices(cfg)
    model = max(cfg.model, 1)
    dp = cfg.dp if cfg.dp > 0 else len(devs) // model
    devs = devs[: dp * model]
    arr = np.asarray(devs).reshape(dp, model)
    return Mesh(arr, (AXIS_DP, AXIS_MODEL))
