"""The synchronous data-parallel learner — core of the TPU rebuild.

Replaces the reference's Spark/parameter-server asynchronous gradient
push/pull (SURVEY.md §2.2, §3.4 [M][P]) with the north-star-mandated design:
one jitted ``train_step`` wrapped in ``shard_map`` over a ``dp`` device
mesh; per-device gradients are allreduced with ``lax.pmean`` (psum/n) over
ICI; parameters, optimizer state, and the target network stay replicated so
the periodic target refresh ("every C pulls: θ⁻ ← θ", SURVEY §3.1 [M]) is a
branchless on-device copy — the moral equivalent of "broadcast θ⁻ from
chip 0" with zero comms, since replicated updates are bitwise identical on
every chip.

Everything — Bellman targets, forward, backward, optimizer, target refresh —
compiles into ONE XLA program per step. The reference crosses the Python↔
Caffe boundary multiple times per minibatch (SURVEY §3.1 hot loop); here the
host only feeds batches and reads back scalar metrics.

TrainState buffers are donated (``donate_argnums=0``), so parameters and
optimizer state are updated in place in HBM with no per-step allocation churn.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from distributed_deep_q_tpu import learning, tracing
from distributed_deep_q_tpu.compat import safe_increment, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_deep_q_tpu.config import TrainConfig
from distributed_deep_q_tpu.models.qnet import (
    stacked_q_apply, stacked_q_forwards)
from distributed_deep_q_tpu.ops.losses import bellman_targets, dqn_loss
from distributed_deep_q_tpu.parallel.mesh import (
    AXIS_DP, AXIS_MODEL, tree_shardings)
from distributed_deep_q_tpu.parallel.multihost import (
    global_batch, put_replicated)


# Adam moment decays, shared by ``make_optimizer`` (the state-structure
# builder) and ``fused_adam_step`` (the hot path) so the two can never
# drift apart — their bitwise equivalence is load-bearing for checkpoints.
ADAM_B1, ADAM_B2 = 0.9, 0.999


class TrainState(flax.struct.PyTreeNode):
    params: Any
    target_params: Any
    opt_state: Any
    step: jax.Array  # int32 scalar


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    """Optimizer chain. The reference PS applied RMSProp/AdaGrad-style
    updates (SURVEY §3.4 [P]); we default to Adam with the same switch.

    For adam the returned transform's ``init`` defines the opt_state
    STRUCTURE (kept exactly as optax builds it, chain included, so
    checkpoints resume across versions) but its ``update`` is NOT on the
    hot path — the train steps run ``fused_adam_step``, which performs
    the same clip+adam math in one tree pass (the optax stack costs
    ~0.05 ms/step at batch 32 in separate passes — the step is
    op-count-bound there). rmsprop keeps the optax update path with
    ``clip_grads``."""
    if cfg.optimizer == "adam":
        opt = optax.adam(cfg.lr, b1=ADAM_B1, b2=ADAM_B2, eps=cfg.adam_eps,
                         mu_dtype=jnp.dtype(cfg.adam_mu_dtype))
    elif cfg.optimizer == "rmsprop":
        opt = optax.rmsprop(cfg.lr, decay=0.95, eps=1e-2, centered=True)
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    if cfg.grad_clip_norm > 0:
        return optax.chain(optax.clip_by_global_norm(cfg.grad_clip_norm),
                           opt)
    return opt


def clip_grads(cfg: TrainConfig, grads: Any,
               gnorm: jax.Array) -> tuple[Any, jax.Array]:
    """Global-norm clip using the ALREADY-computed norm — identical math
    to ``optax.clip_by_global_norm`` (scale by min(1, clip/norm)), one
    tree pass instead of three (its norm + its scale + the metric's
    norm). Returns (clipped grads, the norm for the metric)."""
    if cfg.grad_clip_norm <= 0:
        return grads, gnorm
    scale = jnp.minimum(1.0, cfg.grad_clip_norm
                        / jnp.maximum(gnorm, 1e-12))
    return jax.tree.map(lambda g: g * scale, grads), gnorm


def _locate_adam_state(opt_state: Any):
    """Locate the ScaleByAdamState inside whichever structure
    ``make_optimizer`` built — bare adam (clip off) or
    ``chain(clip_by_global_norm, adam)`` — preserving it exactly so
    checkpoints stay resumable across both. Returns (adam_state,
    rebuild) where ``rebuild(new_adam_state)`` reassembles the full
    opt_state."""
    if isinstance(opt_state[0], optax.ScaleByAdamState):
        adam_state = opt_state[0]

        def rebuild(s):
            return (s,) + tuple(opt_state[1:])
    else:
        inner = opt_state[1]
        adam_state = inner[0]

        def rebuild(s):
            return (opt_state[0], (s,) + tuple(inner[1:])) \
                + tuple(opt_state[2:])
    return adam_state, rebuild


def fused_adam_step(cfg: TrainConfig, grads: Any, opt_state: Any,
                    params: Any, gnorm: jax.Array) -> tuple[Any, Any]:
    """Clip + Adam + parameter update in ONE multi-output fusion per leaf.

    Bitwise-compatible math and state structure with
    ``optax.chain(clip_by_global_norm, adam)`` (the state is the tuple
    ``optax.adam().init`` builds, so checkpoints are interchangeable —
    tests/test_losses.py holds the equivalence). Exists because the step
    is op-count-bound at small batch on this chip (~1.5-4.5 µs fixed
    cost per scheduled fusion, measured): optax runs ~5 tree passes ×
    13 leaves where one pass suffices — the fold measured ~0.05 ms/step
    at batch 32, ~18% of the whole train step.

    Returns (new opt_state, new params).
    """
    opt_state, params, _ = fused_adam_target_step(
        cfg, grads, opt_state, params, None, gnorm, None)
    return opt_state, params


def fused_adam_target_step(
    cfg: TrainConfig, grads: Any, opt_state: Any, params: Any,
    target_params: Any, gnorm: jax.Array, step: jax.Array | None,
) -> tuple[Any, Any, Any]:
    """``fused_adam_step`` with the target refresh folded into the SAME
    per-leaf multi-output fusion.

    The ``lax.cond``-based ``refresh_target`` schedules a whole-tree COPY
    of whichever branch it takes — 13 scheduled copies per step on the
    13-leaf Nature net, pure per-op overhead on the op-count-bound small
    batch step. Folded here the refresh is one extra elementwise output
    per leaf fusion: Polyak ``τ·p₂ + (1−τ)·t`` when ``target_tau`` > 0,
    else ``where(step % C == 0, p₂, t)`` — a select, bitwise-identical
    to the cond's chosen branch. ``step`` is the ALREADY-incremented
    step (the refresh condition matches ``refresh_target``'s).

    With ``target_params=None`` this is plain ``fused_adam_step``
    (returned target tree is ``None``).

    Returns (new opt_state, new params, new target_params).
    """
    adam_state, rebuild = _locate_adam_state(opt_state)
    b1, b2 = ADAM_B1, ADAM_B2
    count = safe_increment(adam_state.count)
    c = count.astype(jnp.float32)
    bc1 = 1.0 - b1 ** c
    bc2 = 1.0 - b2 ** c
    scale = (jnp.minimum(1.0, cfg.grad_clip_norm
                         / jnp.maximum(gnorm, 1e-12))
             if cfg.grad_clip_norm > 0 else jnp.float32(1.0))
    lr, eps = cfg.lr, cfg.adam_eps
    mu_dtype = jnp.dtype(cfg.adam_mu_dtype)
    with_target = target_params is not None
    if with_target:
        if cfg.target_tau > 0:
            tau = cfg.target_tau

            def tleaf(p2, t):
                return tau * p2 + (1.0 - tau) * t
        else:
            do_refresh = step % cfg.target_update_period == 0

            def tleaf(p2, t):
                return jnp.where(do_refresh, p2, t)

    def leaf(g, m, v, p, *rest):
        g = g * scale
        m2 = b1 * m.astype(jnp.float32) + (1.0 - b1) * g
        v2 = b2 * v + (1.0 - b2) * jnp.square(g)
        upd = (m2 / bc1) / (jnp.sqrt(v2 / bc2) + eps)
        p2 = p - lr * upd
        if with_target:
            return m2.astype(mu_dtype), v2, p2, tleaf(p2, rest[0])
        return m2.astype(mu_dtype), v2, p2

    trees = (grads, adam_state.mu, adam_state.nu, params)
    if with_target:
        trees += (target_params,)
    out = jax.tree.map(leaf, *trees)
    treedef = jax.tree_util.tree_structure(grads)
    parts = [jax.tree_util.tree_unflatten(
        treedef, [t[i] for t in jax.tree_util.tree_leaves(
            out, is_leaf=lambda x: isinstance(x, tuple))])
        for i in range(4 if with_target else 3)]
    new_opt = rebuild(adam_state._replace(count=count, mu=parts[0],
                                          nu=parts[1]))
    return new_opt, parts[2], (parts[3] if with_target else None)


def refresh_target(cfg: TrainConfig, params: Any, target_params: Any,
                   step: jax.Array) -> Any:
    """θ⁻ update, shared by both learners: Polyak θ⁻ ← τθ + (1−τ)θ⁻ every
    step when ``target_tau`` > 0, else the hard copy every C steps
    ("every C pulls: θ⁻ ← θ", SURVEY §3.1 [M]) via lax.cond so the copy
    stays off the hot path on non-refresh steps."""
    if cfg.target_tau > 0:
        tau = cfg.target_tau
        return jax.tree.map(lambda p, t: tau * p + (1.0 - tau) * t,
                            params, target_params)
    return lax.cond(
        step % cfg.target_update_period == 0,
        lambda: params,
        lambda: target_params,
    )


# -- flat parameter/moment planes (op-count surgery, PERF.md §3) -----------
#
# The chained device-PER program's scan body used to pay the optimizer as
# per-leaf kernels: on a backend without multi-output fusion (CPU XLA — the
# ratchet's measurement platform) the "one fusion per leaf" fused update
# decomposes into ~5 scheduled fusions PER LEAF, plus a per-leaf stack
# concat feeding the stacked forward and a per-leaf gnorm partial — ~85 of
# the body's ~125 scheduled ops for a 12-leaf Nature net. The fix: carry
# θ/θ⁻ as ONE flat f32 plane and the Adam moments as two more, so the
# whole optimizer is a fixed handful of plane-wide kernels independent of
# leaf count. Layout of the PT plane ([2N], N = total param count): per
# leaf the online and target blocks sit ADJACENT ([θ_i; θ⁻_i] at offset
# 2·off_i), so the stacked ``[2, shape]`` leaf view the vmapped forward
# wants is a contiguous slice — free, where a [P; T] split layout would
# pay a concat per leaf per step. Tree↔plane conversion happens once per
# chunk at the scan boundary, amortized over ``chain`` grad steps.

class PlaneMeta(NamedTuple):
    """Static layout of the flat planes, derived from the param treedef.

    ``upd_map``/``src_map``/``onl`` are host-side constants baked into the
    program: ``upd_map`` sends every PT position to its leaf's online
    position in the [N] update plane (both halves — the target half reuses
    the online update on refresh); ``src_map`` mirrors each target
    position onto its online twin (identity on the online half); ``onl``
    marks the online half."""
    treedef: Any
    shapes: tuple
    sizes: tuple
    offsets: tuple
    n: int
    upd_map: np.ndarray
    src_map: np.ndarray
    onl: np.ndarray


def plane_meta(params: Any) -> PlaneMeta:
    leaves, treedef = jax.tree_util.tree_flatten(params)
    shapes = tuple(leaf.shape for leaf in leaves)
    sizes = tuple(int(np.prod(s, dtype=np.int64)) for s in shapes)
    offsets = tuple(int(o) for o in np.cumsum((0,) + sizes[:-1]))
    n = int(sum(sizes))
    upd_map = np.empty(2 * n, np.int32)
    src_map = np.empty(2 * n, np.int32)
    onl = np.zeros(2 * n, bool)
    for off, size in zip(offsets, sizes):
        o2 = 2 * off
        upd = np.arange(off, off + size, dtype=np.int32)
        upd_map[o2:o2 + size] = upd
        upd_map[o2 + size:o2 + 2 * size] = upd
        src = np.arange(o2, o2 + size, dtype=np.int32)
        src_map[o2:o2 + size] = src
        src_map[o2 + size:o2 + 2 * size] = src
        onl[o2:o2 + size] = True
    return PlaneMeta(treedef, shapes, sizes, offsets, n,
                     upd_map, src_map, onl)


def params_to_plane(meta: PlaneMeta, params: Any,
                    target_params: Any) -> jax.Array:
    """Interleave θ/θ⁻ into the [2N] PT plane (leaf blocks adjacent)."""
    blocks = []
    for p, t in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(target_params)):
        blocks.append(p.reshape(-1).astype(jnp.float32))
        blocks.append(t.reshape(-1).astype(jnp.float32))
    return jnp.concatenate(blocks)


def tree_to_plane(tree: Any) -> jax.Array:
    """Ravel-and-concat a tree into its [N] plane (moment planes keep
    their storage dtype so per-step round trips stay bitwise)."""
    return jnp.concatenate(
        [leaf.reshape(-1) for leaf in jax.tree_util.tree_leaves(tree)])


def plane_stacked_views(meta: PlaneMeta, pt: jax.Array) -> tuple:
    """The [2, shape] stacked leaf views of the PT plane — contiguous
    slices (the layout's whole point), fed to ``stacked_q_apply``."""
    return tuple(
        pt[2 * off:2 * off + 2 * size].reshape((2,) + shape)
        for off, size, shape in zip(meta.offsets, meta.sizes, meta.shapes))


def plane_to_param_trees(meta: PlaneMeta, pt: jax.Array,
                         params: Any, target_params: Any) -> tuple:
    """Inverse of ``params_to_plane`` — dtypes restored per template."""
    new_p, new_t = [], []
    for off, size, shape, tmpl in zip(
            meta.offsets, meta.sizes, meta.shapes,
            jax.tree_util.tree_leaves(params)):
        o2 = 2 * off
        new_p.append(pt[o2:o2 + size].reshape(shape).astype(tmpl.dtype))
        new_t.append(
            pt[o2 + size:o2 + 2 * size].reshape(shape).astype(tmpl.dtype))
    return (jax.tree_util.tree_unflatten(meta.treedef, new_p),
            jax.tree_util.tree_unflatten(meta.treedef, new_t))


def plane_to_tree(meta: PlaneMeta, plane: jax.Array,
                  template: Any) -> Any:
    """Slice an [N] plane back into ``template``'s tree structure."""
    leaves = [
        plane[off:off + size].reshape(shape).astype(tmpl.dtype)
        for off, size, shape, tmpl in zip(
            meta.offsets, meta.sizes, meta.shapes,
            jax.tree_util.tree_leaves(template))]
    return jax.tree_util.tree_unflatten(meta.treedef, leaves)


def fused_plane_adam_target_step(
    cfg: TrainConfig, meta: PlaneMeta, g: jax.Array, m: jax.Array,
    v: jax.Array, count: jax.Array, pt: jax.Array, step: jax.Array,
    gnorm: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """``fused_adam_target_step`` on the flat planes: clip + Adam + the
    parameter/target update as a FIXED number of plane-wide kernels
    (two multiply-adds, two gathers, one select/lerp) regardless of how
    many leaves the net has. Per-element arithmetic is identical to the
    per-leaf version (the maps only permute positions), so the hard
    refresh stays a bitwise select of the freshly-updated online value.
    ``g`` is the [N] online-layout gradient plane (already allreduced);
    ``step`` the already-incremented step. Returns (m2, v2, pt2, count2).
    """
    b1, b2 = ADAM_B1, ADAM_B2
    count2 = safe_increment(count)
    c = count2.astype(jnp.float32)
    bc1 = 1.0 - b1 ** c
    bc2 = 1.0 - b2 ** c
    scale = (jnp.minimum(1.0, cfg.grad_clip_norm
                         / jnp.maximum(gnorm, 1e-12))
             if cfg.grad_clip_norm > 0 else jnp.float32(1.0))
    g = g * scale
    m2 = b1 * m.astype(jnp.float32) + (1.0 - b1) * g
    v2 = b2 * v + (1.0 - b2) * jnp.square(g)
    # lr folded into the denominator: the final update must be a
    # SUBTRACT-OF-A-DIVISION, not subtract-of-a-multiply — a mul feeding
    # the sub is FMA-contractible, and LLVM contracts it in one unroll
    # context but not the other, breaking the chain=k ≡ k × chain=1
    # bitwise guarantee (measured: ~300 one-ulp params diffs per step)
    upd = (m2 / bc1) / ((jnp.sqrt(v2 / bc2) + cfg.adam_eps)
                        * np.float32(1.0 / cfg.lr))
    # candidate value for EVERY PT position: its (fresh) online twin
    p2t = jnp.take(pt, meta.src_map) - jnp.take(upd, meta.upd_map)
    if cfg.target_tau > 0:
        w = jnp.asarray(np.where(meta.onl, 1.0, cfg.target_tau),
                        jnp.float32)
        pt2 = w * p2t + (1.0 - w) * pt
    else:
        take = jnp.asarray(meta.onl) | (
            step % cfg.target_update_period == 0)
        pt2 = jnp.where(take, p2t, pt)
    return m2.astype(jnp.dtype(cfg.adam_mu_dtype)), v2, pt2, count2


def q_step_loss(cfg: TrainConfig, q: jax.Array, q_next_o: jax.Array | None,
                q_next_t: jax.Array, batch: dict[str, jax.Array]):
    """Bellman targets + (Pallas or XLA) weighted Huber — the loss tail
    shared by the tree-carry and plane-carry step cores, so the two paths
    can never drift numerically. Returns (loss, |TD|)."""
    targets = bellman_targets(batch["reward"], batch["discount"],
                              q_next_t, q_next_o, cfg.double_dqn)
    if cfg.use_pallas_loss:
        from distributed_deep_q_tpu.ops.pallas_kernels import (
            fused_dqn_loss)
        return fused_dqn_loss(q, batch["action"],
                              lax.stop_gradient(targets),
                              batch["weight"], cfg.huber_delta)
    return dqn_loss(q, batch["action"], targets, batch["weight"],
                    cfg.huber_delta)


class Learner:
    """Owns the sharded train step for feed-forward Q-nets.

    ``apply_fn(params, obs) -> q`` is the Flax module apply; the sequence
    (R2D2) learner lives in ``parallel/sequence_learner.py``.
    """

    def __init__(
        self,
        apply_fn: Callable[[Any, jax.Array], jax.Array],
        cfg: TrainConfig,
        mesh: Mesh,
    ):
        self.apply_fn = apply_fn
        self.cfg = cfg
        self.mesh = mesh
        self.opt = make_optimizer(cfg)
        self._replicated = NamedSharding(mesh, P())
        self._batch_sharding = NamedSharding(mesh, P(AXIS_DP))
        self._train_step = self._build_train_step()
        # ring steps built lazily, keyed on the (static) frame shape the
        # flat HBM ring's rows decode to
        self._ring_steps: dict[tuple[int, int], Any] = {}
        # fused device-PER steps, keyed on the replay's static geometry
        self._device_per_steps: dict[tuple, Any] = {}

    # -- state -------------------------------------------------------------

    def init_state(self, params: Any) -> TrainState:
        """Build the TrainState on the mesh. With ``model=1`` (every
        current config) everything replicates — the historical, bitwise
        path. A real model axis places each leaf by the declarative
        partition rules instead (``parallel.mesh.DEFAULT_PARTITION_RULES``,
        ISSUE 10): optimizer moments inherit their parameter's spec
        because the rules match tree paths, not leaf names."""
        state = TrainState(
            params=params,
            target_params=jax.tree.map(jnp.copy, params),
            opt_state=self.opt.init(params),
            step=jnp.zeros((), jnp.int32),
        )
        if self.mesh.shape[AXIS_MODEL] <= 1:
            return put_replicated(state, self._replicated)
        return put_replicated(state, tree_shardings(self.mesh, state))

    # -- train step --------------------------------------------------------

    def _step_core(self, state: TrainState, batch: dict[str, jax.Array]):
        """Loss + allreduce + optimizer + target refresh — shared by the
        host-batch and device-ring paths. ``batch`` holds per-device local
        arrays with ``obs``/``next_obs`` already composed."""
        cfg, apply_fn, opt = self.cfg, self.apply_fn, self.opt
        # static at trace time: per-shard batch decides the auto gate
        use_stacked = (cfg.stack_forwards == "on"
                       or (cfg.stack_forwards == "auto"
                           and batch["obs"].shape[0] <= 128))

        def loss_fn(params):
            if use_stacked:
                # ALL the step's forwards — θ(s), θ(s') when double, and
                # θ⁻(s') — as one stacked-weight application: the conv
                # batching rule lowers the whole thing to a single conv
                # chain (models/qnet.py, stacked_q_forwards)
                q, q_next_o, q_next_t = stacked_q_forwards(
                    apply_fn, params, state.target_params,
                    batch["obs"], batch["next_obs"], cfg.double_dqn)
            elif cfg.double_dqn and cfg.fuse_double_forward:
                # one conv application for s AND s' (cfg docstring): the
                # split's s' half carries zero cotangents back (action
                # selection must not backprop into the online net)
                qq = apply_fn(params, jnp.concatenate(
                    [batch["obs"], batch["next_obs"]], axis=0))
                q, q_next_o = jnp.split(qq, 2, axis=0)
                q_next_o = lax.stop_gradient(q_next_o)
                q_next_t = apply_fn(state.target_params,
                                    batch["next_obs"])
            else:
                q = apply_fn(params, batch["obs"])
                q_next_o = (apply_fn(params, batch["next_obs"])
                            if cfg.double_dqn else None)
                # action selection must not backprop into the online net
                if q_next_o is not None:
                    q_next_o = lax.stop_gradient(q_next_o)
                q_next_t = apply_fn(state.target_params,
                                    batch["next_obs"])
            loss, td_abs = q_step_loss(cfg, q, q_next_o, q_next_t, batch)
            return loss, (td_abs, q)

        (loss, (td_abs, q)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)

        # THE collective: gradient allreduce over ICI — replaces the
        # reference's PS push/pull (north star [M]).
        grads = lax.pmean(grads, AXIS_DP)
        loss = lax.pmean(loss, AXIS_DP)
        q_mean = lax.pmean(jnp.mean(q), AXIS_DP)

        gnorm = optax.global_norm(grads)
        step = state.step + 1
        if cfg.optimizer == "adam":
            # clip AND target refresh folded into the one-pass fused
            # update (op-count-bound step — see fused_adam_target_step)
            opt_state, params, target_params = fused_adam_target_step(
                cfg, grads, state.opt_state, state.params,
                state.target_params, gnorm, step)
        else:
            grads, gnorm = clip_grads(cfg, grads, gnorm)
            updates, opt_state = opt.update(grads, state.opt_state,
                                            state.params)
            params = optax.apply_updates(state.params, updates)
            target_params = refresh_target(cfg, params,
                                           state.target_params, step)
        new_state = TrainState(params, target_params, opt_state, step)
        metrics = {
            "loss": loss,
            "q_mean": q_mean,
            "grad_norm": gnorm,
        }
        return new_state, metrics, td_abs

    def _build_train_step(self):
        def step_fn(state: TrainState, batch: dict[str, jax.Array]):
            return self._step_core(state, batch)

        sharded = shard_map(
            step_fn,
            mesh=self.mesh,
            in_specs=(P(), P(AXIS_DP)),
            out_specs=(P(), P(), P(AXIS_DP)),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=0)

    def _build_ring_step(self, frame_shape: tuple[int, int]):
        """Train step fed by the device-resident frame ring: pixels are
        gathered/stacked per device from the local ring shard (indices are
        shard-local), so only [B, stack] int32 + [B] scalars cross the
        host boundary (SURVEY §7.3 item 1)."""
        from distributed_deep_q_tpu.replay.device_ring import compose_stacks

        def step_fn(state: TrainState, ring: jax.Array,
                    batch: dict[str, jax.Array]):
            composed = {
                "obs": compose_stacks(ring, batch["oidx"], batch["valid"],
                                      frame_shape),
                "next_obs": compose_stacks(ring, batch["noidx"],
                                           batch["nvalid"], frame_shape),
                "action": batch["action"],
                "reward": batch["reward"],
                "discount": batch["discount"],
                "weight": batch["weight"],
            }
            return self._step_core(state, composed)

        sharded = shard_map(
            step_fn,
            mesh=self.mesh,
            in_specs=(P(), P(AXIS_DP), P(AXIS_DP)),
            out_specs=(P(), P(), P(AXIS_DP)),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=0)

    def train_step_from_ring(self, state: TrainState, ring: jax.Array,
                             batch: dict[str, Any],
                             frame_shape: tuple[int, int] = (84, 84)):
        """One DP step sampling pixels from the HBM ring (device replay)."""
        key = tuple(frame_shape)
        if key not in self._ring_steps:
            self._ring_steps[key] = self._build_ring_step(key)
        return self._ring_steps[key](state, ring, batch)

    def _build_device_per_step(self, spec: tuple, chain: int,
                               donate: bool = True):
        """Fused prioritized step (replay/device_per.py): per shard —
        validity mask → inverse-CDF prioritized draw → on-device stack +
        n-step composition → DQN step → same-step priority scatter. The
        host ships per-slot cursors/sizes, β, and sampling keys; NOTHING
        is read back (the per-sample |TD| never leaves the device).

        ``chain`` > 1 amortizes dispatch: the SAMPLE program draws all
        ``chain`` batches against chunk-start priorities in ONE
        straight-line vectorized block (no scan — per-step draws have no
        carry, and scanned bodies re-touch capacity-sized arrays per
        iteration), while the TRAIN program ``lax.scan``s the ``chain``
        optimizer steps and priority scatters strictly in order.
        Within-chunk priority staleness ≤ chain steps — the same bound
        the host path's ``DelayedPriorityWriteback(depth=8)`` accepts.
        Across chunks everything is fresh.

        Data plane (round 5): the sample program composes metadata from
        the per-row ``build_meta_pack`` (two row gathers per sample) and
        copies each sample's combined obs+next-obs pixel window with the
        Pallas row-DMA kernel (``ops/ring_gather.py`` — 3 ms vs 44 ms
        for the tiled XLA gathers it replaced at the 1M-ring shape); the
        train program slices obs/next-obs out of the windows, applies the
        validity bit-planes, and runs the DQN step. Keys stay
        host-generated (a fold_in-keyed program executed the ring gather
        ~200× slower — measured minimal pair, r3)."""
        (slot_cap, slot_pad, rowb, row_len, stack, n_step, gamma,
         frame_shape, per_shard, alpha, eps, num_shards, interpret) = spec
        from distributed_deep_q_tpu.ops.ring_gather import gather_windows
        from distributed_deep_q_tpu.replay.device_per import (
            build_meta_pack, fused_sample_draw_packed, fused_sample_prep,
            scatter_priorities, stack_rows_to_obs)

        S = P(AXIS_DP)
        SK = P(None, AXIS_DP)   # [chain, B]-stacked outputs, batch-sharded
        SK3 = P(None, AXIS_DP, None)
        SWIN = P(None, AXIS_DP, None, None)
        window = stack + n_step
        n_win = chain * per_shard
        rowp = rowb // 4        # int32 elements per padded frame row

        def sample_fn(keys, frames, action, reward, done, boundary, prio,
                      cursors, sizes, betas):
            shard_rows = {
                "action": action, "reward": reward,
                "done": done, "boundary": boundary, "prio": prio,
            }
            pm, cdf, mass, n_glob = fused_sample_prep(
                shard_rows, cursors, sizes, slot_cap, stack, n_step)
            pack = build_meta_pack(action, reward, done, boundary,
                                   slot_cap, stack, n_step, gamma)
            # keys arrives [1, chain, 2] per shard (sharded over dim 0)
            metas, ws, idxs = fused_sample_draw_packed(
                keys[0], pack, pm, cdf, mass, n_glob, per_shard,
                slot_cap, slot_pad, stack, n_step, betas, num_shards)
            win = gather_windows(ws.reshape(-1), frames, n=n_win,
                                 w=window, rowb=rowb, interpret=interpret)
            return metas, win.reshape(chain, per_shard, window, rowp), idxs

        meta_spec = {"action": SK, "reward": SK, "discount": SK,
                     "weight": SK, "ovalid": SK3, "nvalid": SK3}
        sample = jax.jit(shard_map(
            sample_fn, mesh=self.mesh,
            in_specs=(S, S, S, S, S, S, S, S, S, P()),
            out_specs=(meta_spec, SWIN, SK),
            check_vma=False))

        cfg = self.cfg
        # static gates (spec's per_shard is the in-shard batch, the same
        # quantity _step_core's auto gate reads off the traced batch)
        use_stacked = (cfg.stack_forwards == "on"
                       or (cfg.stack_forwards == "auto"
                           and per_shard <= 128))
        # the flat plane-carry layout concatenates every leaf into one
        # replicated f32 plane, which is incompatible with per-leaf
        # model-axis partition rules (parallel.mesh) — a real model axis
        # keeps the per-leaf tree path where rule shardings apply
        use_plane = (use_stacked and cfg.optimizer == "adam"
                     and self.mesh.shape[AXIS_MODEL] <= 1)

        def unpack_batch(batch, w):
            batch = dict(batch)
            ovalid = batch.pop("ovalid")
            nvalid = batch.pop("nvalid")
            # unpack int32 → pixel bytes (little-endian round trip
            # with the host's uint8.view(int32), verified both
            # platforms), drop the DMA row padding
            pix = lax.bitcast_convert_type(w, jnp.uint8)
            pix = pix.reshape(w.shape[:2] + (rowp * 4,))[:, :, :row_len]
            obs = pix[:, :stack] * ovalid[..., None]
            nobs = pix[:, n_step:n_step + stack] * nvalid[..., None]
            batch["obs"] = stack_rows_to_obs(obs, frame_shape)
            batch["next_obs"] = stack_rows_to_obs(nobs, frame_shape)
            return batch

        def tree_train_fn(state: TrainState, metas, win, idxs, prio, maxp):
            def body(carry, xs):
                state, prio, maxp = carry
                batch, w, idx = xs
                batch = unpack_batch(batch, w)
                state, metrics, td_abs = self._step_core(state, batch)
                prio, maxp = scatter_priorities(prio, maxp, idx, td_abs,
                                                alpha, eps)
                return (state, prio, maxp), metrics

            (state, prio, maxp), metrics = lax.scan(
                body, (state, prio, maxp), (metas, win, idxs))
            return state, prio, maxp, metrics

        def plane_train_fn(state: TrainState, metas, win, idxs, prio,
                           maxp):
            # The op-count-surgery body (PERF.md §3): θ/θ⁻ ride the scan
            # carry as ONE flat plane (moments as two more), so the whole
            # optimizer + target refresh is a fixed handful of plane-wide
            # kernels instead of ~5 scheduled fusions per leaf, and every
            # stacked leaf view feeding the vmapped forward is a free
            # contiguous slice. Tree↔plane conversion sits OUTSIDE the
            # scan, amortized over the chain. Per-step math is the same
            # fused clip+Adam+refresh (see fused_plane_adam_target_step);
            # the one deliberate deviation is the gradient norm, computed
            # as a single flat reduce over the g-plane rather than
            # optax.global_norm's per-leaf partial sums — same value to
            # f32 ulp, one kernel instead of thirteen.
            meta = plane_meta(state.params)
            adam_state, rebuild = _locate_adam_state(state.opt_state)
            pt = params_to_plane(meta, state.params, state.target_params)
            m = tree_to_plane(adam_state.mu)
            v = tree_to_plane(adam_state.nu)

            def body(carry, xs):
                if cfg.learn_metrics:
                    pt, m, v, cnt, step, prio, maxp, lmp = carry
                else:
                    pt, m, v, cnt, step, prio, maxp = carry
                batch, w, idx = xs
                batch = unpack_batch(batch, w)
                step2 = step + 1

                def loss_fn(views):
                    stacked = jax.tree_util.tree_unflatten(
                        meta.treedef, list(views))
                    q, q_next_o, q_next_t = stacked_q_apply(
                        self.apply_fn, stacked, batch["obs"],
                        batch["next_obs"], cfg.double_dqn)
                    loss, td_abs = q_step_loss(cfg, q, q_next_o,
                                               q_next_t, batch)
                    return loss, (td_abs, q)

                (loss, (td_abs, q)), gv = jax.value_and_grad(
                    loss_fn, has_aux=True)(plane_stacked_views(meta, pt))
                # online halves only — the target halves carry zero
                # cotangents (targets are stop-gradded in the loss)
                g = jnp.concatenate([x[0].reshape(-1) for x in gv])
                g = lax.pmean(g, AXIS_DP)
                loss = lax.pmean(loss, AXIS_DP)
                q_mean = lax.pmean(jnp.mean(q), AXIS_DP)
                gnorm = jnp.sqrt(jnp.sum(jnp.square(g)))
                m, v, pt, cnt = fused_plane_adam_target_step(
                    cfg, meta, g, m, v, cnt, pt, step2, gnorm)
                prio, maxp = scatter_priorities(prio, maxp, idx, td_abs,
                                                alpha, eps)
                metrics = {"loss": loss, "q_mean": q_mean,
                           "grad_norm": gnorm}
                if cfg.learn_metrics:
                    # learning-dynamics plane (learning.py): pure-jnp
                    # accumulation into the carry — the training math
                    # above is untouched, so the gate-off path stays
                    # bitwise identical (test_learning_metrics)
                    lmp = learning.lm_update(
                        lmp, cfg=cfg, td_abs=td_abs,
                        weight=batch["weight"], loss=loss, q=q,
                        q_mean=q_mean, gnorm=gnorm, step=step2,
                        alpha=alpha, eps=eps)
                    return (pt, m, v, cnt, step2, prio, maxp, lmp), \
                        metrics
                return (pt, m, v, cnt, step2, prio, maxp), metrics

            carry0 = (pt, m, v, adam_state.count, state.step, prio, maxp)
            if cfg.learn_metrics:
                carry0 = carry0 + (learning.lm_init(),)
                (pt, m, v, cnt, step, prio, maxp, lmp), metrics = \
                    lax.scan(body, carry0, (metas, win, idxs))
                metrics = dict(metrics)
                # ONE cross-shard reduction per dispatch, outside the
                # scan; replicated, so the trailing P() out-spec covers
                # the new dict leaf unchanged
                metrics["learn_plane"] = learning.lm_finalize(
                    lmp, AXIS_DP)
            else:
                (pt, m, v, cnt, step, prio, maxp), metrics = lax.scan(
                    body, carry0, (metas, win, idxs))
            params, target_params = plane_to_param_trees(
                meta, pt, state.params, state.target_params)
            new_opt = rebuild(adam_state._replace(
                count=cnt, mu=plane_to_tree(meta, m, adam_state.mu),
                nu=plane_to_tree(meta, v, adam_state.nu)))
            new_state = TrainState(params, target_params, new_opt, step)
            return new_state, prio, maxp, metrics

        train_fn = plane_train_fn if use_plane else tree_train_fn

        # donate every input that aliases an updated output: the state
        # tree (0) and the priority plane/max (4, 5) are rewritten each
        # call, so XLA writes the new values in place instead of
        # scheduling defensive copies of the (large) param/priority
        # buffers. metas/win/idxs are consumed exactly once but have no
        # same-shaped output to alias, so donating them buys nothing.
        train = jax.jit(shard_map(
            train_fn, mesh=self.mesh,
            in_specs=(P(), meta_spec, SWIN, SK, S, P()),
            out_specs=(P(), S, P(), P()),
            check_vma=False),
            donate_argnums=(0, 4, 5) if donate else ())
        return sample, train

    def train_steps_device_per(self, state: TrainState, rows, cursors,
                               sizes, betas: np.ndarray, keys: np.ndarray,
                               spec: tuple):
        """``len(betas)`` fused sample+train+priority-update steps on
        device PER in ONE two-program dispatch (zero reads back). ``keys``
        is host-generated ``[D, chain, 2]`` uint32 (the caller owns key
        derivation — see ``Solver.train_steps_device_per``). Returns
        (state, new_prio, new_maxp, metrics with a leading [chain] axis).
        """
        chain = len(betas)
        cache_key = (spec, chain)
        if cache_key not in self._device_per_steps:
            self._device_per_steps[cache_key] = \
                self._build_device_per_step(spec, chain)
        sample, train = self._device_per_steps[cache_key]

        def feed(x, dtype=None):
            # host numpy feeds pass through asarray; multi-host global
            # jax arrays (assembled by the solver) must not be copied
            return x if isinstance(x, jax.Array) else np.asarray(x, dtype)

        # spans time the host-side DISPATCH of the two async device
        # programs, not device execution (no block_until_ready here — the
        # zero-readback contract holds); both calls stay outside jit so
        # the tracer's host side effects never enter a traced function
        with tracing.span("sample"):
            metas, win, idx = sample(keys, rows.frames, rows.action,
                                     rows.reward, rows.done, rows.boundary,
                                     rows.prio, feed(cursors), feed(sizes),
                                     feed(betas, np.float32))
        with tracing.span("train_step"):
            return train(state, metas, win, idx, rows.prio, rows.maxp)

    def train_step(self, state: TrainState, batch: dict[str, Any]):
        """One synchronous DP gradient step.

        Single-process: ``batch`` arrays have global leading dim B
        (divisible by mesh dp size). Multi-host (multi-controller JAX,
        SURVEY §5.8): each process passes its LOCAL B/process_count rows —
        its own replay shard's sample — and the global array is assembled
        here. Returns (new_state, metrics dict of replicated scalars,
        |TD| [B] batch-sharded, for PER priority updates).
        """
        with tracing.span("train_step"):  # host dispatch, outside the jit
            return self._train_step(state, global_batch(
                self._batch_sharding, batch))
