"""The synchronous data-parallel learner — core of the TPU rebuild.

Replaces the reference's Spark/parameter-server asynchronous gradient
push/pull (SURVEY.md §2.2, §3.4 [M][P]) with the north-star-mandated design:
one jitted ``train_step`` wrapped in ``shard_map`` over a ``dp`` device
mesh; per-device gradients are allreduced with ``lax.pmean`` (psum/n) over
ICI; parameters, optimizer state, and the target network stay replicated so
the periodic target refresh ("every C pulls: θ⁻ ← θ", SURVEY §3.1 [M]) is a
branchless on-device copy — the moral equivalent of "broadcast θ⁻ from
chip 0" with zero comms, since replicated updates are bitwise identical on
every chip.

Everything — Bellman targets, forward, backward, optimizer, target refresh —
compiles into ONE XLA program per step. The reference crosses the Python↔
Caffe boundary multiple times per minibatch (SURVEY §3.1 hot loop); here the
host only feeds batches and reads back scalar metrics.

TrainState buffers are donated (``donate_argnums=0``), so parameters and
optimizer state are updated in place in HBM with no per-step allocation churn.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_deep_q_tpu.config import TrainConfig
from distributed_deep_q_tpu.ops.losses import bellman_targets, dqn_loss
from distributed_deep_q_tpu.parallel.mesh import AXIS_DP
from distributed_deep_q_tpu.parallel.multihost import (
    global_batch, put_replicated)


class TrainState(flax.struct.PyTreeNode):
    params: Any
    target_params: Any
    opt_state: Any
    step: jax.Array  # int32 scalar


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    """Optimizer chain. The reference PS applied RMSProp/AdaGrad-style
    updates (SURVEY §3.4 [P]); we default to Adam with the same switch."""
    if cfg.optimizer == "adam":
        opt = optax.adam(cfg.lr, eps=cfg.adam_eps)
    elif cfg.optimizer == "rmsprop":
        opt = optax.rmsprop(cfg.lr, decay=0.95, eps=1e-2, centered=True)
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    if cfg.grad_clip_norm > 0:
        return optax.chain(optax.clip_by_global_norm(cfg.grad_clip_norm), opt)
    return opt


def refresh_target(cfg: TrainConfig, params: Any, target_params: Any,
                   step: jax.Array) -> Any:
    """θ⁻ update, shared by both learners: Polyak θ⁻ ← τθ + (1−τ)θ⁻ every
    step when ``target_tau`` > 0, else the hard copy every C steps
    ("every C pulls: θ⁻ ← θ", SURVEY §3.1 [M]) via lax.cond so the copy
    stays off the hot path on non-refresh steps."""
    if cfg.target_tau > 0:
        tau = cfg.target_tau
        return jax.tree.map(lambda p, t: tau * p + (1.0 - tau) * t,
                            params, target_params)
    return lax.cond(
        step % cfg.target_update_period == 0,
        lambda: params,
        lambda: target_params,
    )


class Learner:
    """Owns the sharded train step for feed-forward Q-nets.

    ``apply_fn(params, obs) -> q`` is the Flax module apply; the sequence
    (R2D2) learner lives in ``parallel/sequence_learner.py``.
    """

    def __init__(
        self,
        apply_fn: Callable[[Any, jax.Array], jax.Array],
        cfg: TrainConfig,
        mesh: Mesh,
    ):
        self.apply_fn = apply_fn
        self.cfg = cfg
        self.mesh = mesh
        self.opt = make_optimizer(cfg)
        self._replicated = NamedSharding(mesh, P())
        self._batch_sharding = NamedSharding(mesh, P(AXIS_DP))
        self._train_step = self._build_train_step()
        # ring steps built lazily, keyed on the (static) frame shape the
        # flat HBM ring's rows decode to
        self._ring_steps: dict[tuple[int, int], Any] = {}
        # fused device-PER steps, keyed on the replay's static geometry
        self._device_per_steps: dict[tuple, Any] = {}

    # -- state -------------------------------------------------------------

    def init_state(self, params: Any) -> TrainState:
        """Build a fully-replicated TrainState on the mesh."""
        state = TrainState(
            params=params,
            target_params=jax.tree.map(jnp.copy, params),
            opt_state=self.opt.init(params),
            step=jnp.zeros((), jnp.int32),
        )
        return put_replicated(state, self._replicated)

    # -- train step --------------------------------------------------------

    def _step_core(self, state: TrainState, batch: dict[str, jax.Array]):
        """Loss + allreduce + optimizer + target refresh — shared by the
        host-batch and device-ring paths. ``batch`` holds per-device local
        arrays with ``obs``/``next_obs`` already composed."""
        cfg, apply_fn, opt = self.cfg, self.apply_fn, self.opt

        def loss_fn(params):
            q = apply_fn(params, batch["obs"])
            q_next_t = apply_fn(state.target_params, batch["next_obs"])
            q_next_o = (apply_fn(params, batch["next_obs"])
                        if cfg.double_dqn else None)
            # action selection must not backprop into the online net
            if q_next_o is not None:
                q_next_o = lax.stop_gradient(q_next_o)
            targets = bellman_targets(
                batch["reward"], batch["discount"], q_next_t,
                q_next_o, cfg.double_dqn)
            if cfg.use_pallas_loss:
                from distributed_deep_q_tpu.ops.pallas_kernels import (
                    fused_dqn_loss)
                loss, td_abs = fused_dqn_loss(
                    q, batch["action"], lax.stop_gradient(targets),
                    batch["weight"], cfg.huber_delta)
            else:
                loss, td_abs = dqn_loss(
                    q, batch["action"], targets, batch["weight"],
                    cfg.huber_delta)
            return loss, (td_abs, q)

        (loss, (td_abs, q)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)

        # THE collective: gradient allreduce over ICI — replaces the
        # reference's PS push/pull (north star [M]).
        grads = lax.pmean(grads, AXIS_DP)
        loss = lax.pmean(loss, AXIS_DP)
        q_mean = lax.pmean(jnp.mean(q), AXIS_DP)

        updates, opt_state = opt.update(grads, state.opt_state,
                                        state.params)
        params = optax.apply_updates(state.params, updates)
        step = state.step + 1

        target_params = refresh_target(cfg, params, state.target_params, step)
        new_state = TrainState(params, target_params, opt_state, step)
        metrics = {
            "loss": loss,
            "q_mean": q_mean,
            "grad_norm": optax.global_norm(grads),
        }
        return new_state, metrics, td_abs

    def _build_train_step(self):
        def step_fn(state: TrainState, batch: dict[str, jax.Array]):
            return self._step_core(state, batch)

        sharded = shard_map(
            step_fn,
            mesh=self.mesh,
            in_specs=(P(), P(AXIS_DP)),
            out_specs=(P(), P(), P(AXIS_DP)),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=0)

    def _build_ring_step(self, frame_shape: tuple[int, int]):
        """Train step fed by the device-resident frame ring: pixels are
        gathered/stacked per device from the local ring shard (indices are
        shard-local), so only [B, stack] int32 + [B] scalars cross the
        host boundary (SURVEY §7.3 item 1)."""
        from distributed_deep_q_tpu.replay.device_ring import compose_stacks

        def step_fn(state: TrainState, ring: jax.Array,
                    batch: dict[str, jax.Array]):
            composed = {
                "obs": compose_stacks(ring, batch["oidx"], batch["valid"],
                                      frame_shape),
                "next_obs": compose_stacks(ring, batch["noidx"],
                                           batch["nvalid"], frame_shape),
                "action": batch["action"],
                "reward": batch["reward"],
                "discount": batch["discount"],
                "weight": batch["weight"],
            }
            return self._step_core(state, composed)

        sharded = shard_map(
            step_fn,
            mesh=self.mesh,
            in_specs=(P(), P(AXIS_DP), P(AXIS_DP)),
            out_specs=(P(), P(), P(AXIS_DP)),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=0)

    def train_step_from_ring(self, state: TrainState, ring: jax.Array,
                             batch: dict[str, Any],
                             frame_shape: tuple[int, int] = (84, 84)):
        """One DP step sampling pixels from the HBM ring (device replay)."""
        key = tuple(frame_shape)
        if key not in self._ring_steps:
            self._ring_steps[key] = self._build_ring_step(key)
        return self._ring_steps[key](state, ring, batch)

    def _build_device_per_step(self, spec: tuple, chain: int):
        """Fused prioritized step (replay/device_per.py): per shard —
        validity mask → inverse-CDF prioritized draw → on-device stack +
        n-step composition → DQN step → same-step priority scatter. The
        host ships per-slot cursors/sizes, β, and sampling keys; NOTHING
        is read back (the per-sample |TD| never leaves the device).

        ``chain`` > 1 amortizes dispatch: each program ``lax.scan``s its
        body ``chain`` times per call, so the host pays flush/cursor/key
        bookkeeping and TWO dispatches per ``chain`` grad steps instead of
        per step. Semantics of the chained chunk: the SAMPLE program draws
        all ``chain`` batches against the priorities as of chunk start
        (within-chunk staleness ≤ chain steps — the same bound the host
        path's ``DelayedPriorityWriteback(depth=8)`` already accepts),
        while the TRAIN program applies the ``chain`` optimizer steps and
        priority scatters strictly in order. Across chunks everything is
        fresh."""
        (slot_cap, stack, n_step, gamma, frame_shape, per_shard, alpha,
         eps, num_shards) = spec
        from distributed_deep_q_tpu.replay.device_per import (
            fused_sample_draw_many, fused_sample_prep, gather_rows,
            scatter_priorities, stack_rows_to_obs)

        S = P(AXIS_DP)
        SK = P(None, AXIS_DP)  # [chain, B]-stacked outputs, batch-sharded

        # TWO programs, not one, and NO key derivation on device. Two
        # measured XLA:TPU pathologies shape this structure (each costs a
        # full relayout copy of the frame ring per step — 29 ms at 1M):
        # 1. a program where the gathered pixels flow into the CNN (or out
        #    through a transpose) back-propagates the consumer layout onto
        #    the ring operand;
        # 2. a program whose sampling key comes from jax.random.fold_in
        #    executes the ring gather ~200× slower than the same program
        #    with the key as a plain argument (minimal pair measured:
        #    0.05 ms vs 8.5 ms at 262k rows).
        # So: the sample program takes per-shard keys as an argument
        # (host-generated, ~bytes/step — the same plane that ships
        # cursors), returns gather-natural flat stacks, and the train
        # program does the reshape + CNN + priority scatter.

        def sample_fn(keys, frames, action, reward, done, boundary, prio,
                      cursors, sizes, betas):
            shard_rows = {
                "action": action, "reward": reward,
                "done": done, "boundary": boundary, "prio": prio,
            }
            # NO scan anywhere in the sample program: the per-step draws
            # have no carry (sampling is defined against chunk-start
            # priorities), so all chain batches are drawn/composed in one
            # straight-line vectorized block — every capacity-sized array
            # (mask, CDF, metadata rows, the frame ring) is touched ONCE
            # per chunk. The scanned version re-touched the [cap_local]
            # metadata rows per iteration (round-4 measured the 1M-ring
            # in-scan step at 3.1 ms vs 1.79 ms at 65k on identical
            # [B]-scale math — capacity-sized scan traffic).
            pm, cdf, mass, n_glob = fused_sample_prep(
                shard_rows, cursors, sizes, slot_cap, stack, n_step)
            # keys arrives [1, chain, 2] per shard (sharded over dim 0)
            metas, oflats, ovalids, nflats, nvalids, idxs = \
                fused_sample_draw_many(
                    keys[0], shard_rows, pm, cdf, mass, n_glob,
                    per_shard, slot_cap, stack, n_step, gamma, betas,
                    num_shards)
            batches = dict(metas)
            batches["obs_rows"] = gather_rows(frames, oflats, ovalids)
            batches["nobs_rows"] = gather_rows(frames, nflats, nvalids)
            return batches, idxs

        sample = jax.jit(shard_map(
            sample_fn, mesh=self.mesh,
            in_specs=(S, S, S, S, S, S, S, S, S, P()),
            out_specs=({k: SK for k in ("obs_rows", "nobs_rows", "action",
                                        "reward", "discount", "weight")},
                       SK),
            check_vma=False))

        def train_fn(state: TrainState, batches, idxs, prio, maxp):
            def body(carry, batch_idx):
                state, prio, maxp = carry
                batch, idx = batch_idx
                batch = dict(batch)
                batch["obs"] = stack_rows_to_obs(batch.pop("obs_rows"),
                                                 frame_shape)
                batch["next_obs"] = stack_rows_to_obs(
                    batch.pop("nobs_rows"), frame_shape)
                state, metrics, td_abs = self._step_core(state, batch)
                prio, maxp = scatter_priorities(prio, maxp, idx, td_abs,
                                                alpha, eps)
                return (state, prio, maxp), metrics

            (state, prio, maxp), metrics = lax.scan(
                body, (state, prio, maxp), (batches, idxs))
            return state, prio, maxp, metrics

        train = jax.jit(shard_map(
            train_fn, mesh=self.mesh,
            in_specs=(P(), {k: SK for k in ("obs_rows", "nobs_rows",
                                            "action", "reward", "discount",
                                            "weight")}, SK, S, P()),
            out_specs=(P(), S, P(), P()),
            check_vma=False), donate_argnums=(0, 3, 4))
        return sample, train

    def train_steps_device_per(self, state: TrainState, rows, cursors,
                               sizes, betas: np.ndarray, keys: np.ndarray,
                               spec: tuple):
        """``len(betas)`` fused sample+train+priority-update steps on
        device PER in ONE two-program dispatch (zero reads back). ``keys``
        is host-generated ``[D, chain, 2]`` uint32 (the caller owns key
        derivation — see ``Solver.train_steps_device_per``). Returns
        (state, new_prio, new_maxp, metrics with a leading [chain] axis).
        """
        chain = len(betas)
        cache_key = (spec, chain)
        if cache_key not in self._device_per_steps:
            self._device_per_steps[cache_key] = \
                self._build_device_per_step(spec, chain)
        sample, train = self._device_per_steps[cache_key]
        batch, idx = sample(keys, rows.frames, rows.action,
                            rows.reward, rows.done, rows.boundary,
                            rows.prio, np.asarray(cursors),
                            np.asarray(sizes),
                            np.asarray(betas, np.float32))
        return train(state, batch, idx, rows.prio, rows.maxp)

    def train_step(self, state: TrainState, batch: dict[str, Any]):
        """One synchronous DP gradient step.

        Single-process: ``batch`` arrays have global leading dim B
        (divisible by mesh dp size). Multi-host (multi-controller JAX,
        SURVEY §5.8): each process passes its LOCAL B/process_count rows —
        its own replay shard's sample — and the global array is assembled
        here. Returns (new_state, metrics dict of replicated scalars,
        |TD| [B] batch-sharded, for PER priority updates).
        """
        return self._train_step(state, global_batch(self._batch_sharding,
                                                    batch))
