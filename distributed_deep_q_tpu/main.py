"""CLI entry point (SURVEY.md §1 L6, §2 "Entry/CLI" [M]).

Reference surface kept: a ``main.py`` with ``--backend`` and train / eval /
play modes plus hyperparameter flags. Presets mirror the BASELINE.json
config matrix; any field is overridable with ``--set path=value``.

Examples:
    python -m distributed_deep_q_tpu.main train --preset cartpole --backend cpu
    python -m distributed_deep_q_tpu.main train --preset pong --backend tpu
    python -m distributed_deep_q_tpu.main eval --preset cartpole --backend cpu
"""

from __future__ import annotations

import argparse
import json
import sys

from distributed_deep_q_tpu.config import add_config_flags, config_from_args


def _maybe_restore(solver, cfg) -> int | None:
    """Load the newest Orbax snapshot into ``solver`` when a checkpoint dir
    is configured; returns the restored step (None if nothing to restore)."""
    if not cfg.train.checkpoint_dir:
        return None
    from distributed_deep_q_tpu.utils.checkpoint import Checkpointer
    ckpt = Checkpointer(cfg.train.checkpoint_dir)
    if ckpt.latest_step() is None:
        return None
    solver.state, _ = ckpt.restore(solver.state)
    return solver.step


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="distributed_deep_q_tpu")
    parser.add_argument("mode", choices=["train", "eval", "play"],
                        help="train: run the training loop; eval: greedy "
                             "rollouts; play: single greedy episode with "
                             "per-step printout")
    add_config_flags(parser)
    parser.add_argument("--metrics-jsonl", default="",
                        help="write structured metrics to this JSONL file")
    parser.add_argument("--distributed", action="store_true",
                        help="run the actor/learner RPC topology instead of "
                             "the single-process loop")
    args = parser.parse_args(argv)
    cfg = config_from_args(args)

    # Multi-host bring-up (config 5): when --set mesh.num_processes=N (+
    # mesh.coordinator, mesh.process_id) is given, every process runs this
    # same CLI command and connects here, before any backend init. No-op in
    # the default single-process case.
    from distributed_deep_q_tpu.parallel.multihost import initialize_multihost
    initialize_multihost(cfg.mesh)

    # Import past flag parsing so --help never initializes JAX backends.
    from distributed_deep_q_tpu.metrics import Metrics
    from distributed_deep_q_tpu.train import evaluate, train_single_process

    if args.mode == "train":
        if args.distributed:
            try:
                from distributed_deep_q_tpu.actors.supervisor import (
                    train_distributed)
            except ImportError as e:
                print(f"error: distributed topology unavailable: {e}",
                      file=sys.stderr)
                return 2
            summary = train_distributed(cfg, metrics=Metrics(
                args.metrics_jsonl or None))
        else:
            summary = train_single_process(cfg, metrics=Metrics(
                args.metrics_jsonl or None))
        summary.pop("solver", None)
        print(json.dumps({"mode": "train", **{
            k: v for k, v in summary.items()
            if isinstance(v, (int, float, str))}}))
        return 0

    if args.mode == "eval":
        import numpy as np
        from distributed_deep_q_tpu.actors.game import make_env
        env = make_env(cfg.env, seed=cfg.train.seed)
        cfg.net.num_actions = env.num_actions
        solver = _build_solver(cfg, env)
        restored = _maybe_restore(solver, cfg)
        if cfg.net.kind == "r2d2":
            from distributed_deep_q_tpu.train import evaluate_recurrent
            ret = evaluate_recurrent(solver, cfg)
        else:
            ret = evaluate(solver, cfg)
        print(json.dumps({"mode": "eval", "eval_return": ret,
                          "episodes": cfg.train.eval_episodes,
                          "restored_step": restored}))
        return 0

    if args.mode == "play":
        import numpy as np
        from distributed_deep_q_tpu.actors.game import FrameStacker, make_env
        env = make_env(cfg.env, seed=cfg.train.seed)
        cfg.net.num_actions = env.num_actions
        solver = _build_solver(cfg, env)
        _maybe_restore(solver, cfg)
        rng = np.random.default_rng(cfg.train.seed)
        recurrent = cfg.net.kind == "r2d2"
        carry = solver.initial_state(1) if recurrent else None
        stacker = (FrameStacker(env.obs_shape, cfg.env.stack)
                   if env.obs_dtype == np.uint8 else None)
        obs, over, t, ep_ret = env.reset(), False, 0, 0.0
        if stacker:
            obs = stacker.reset(obs)
        while not over:
            if recurrent:
                a, carry = solver.act(np.asarray(obs), carry,
                                      cfg.actors.eval_eps, rng)
            else:
                a = solver.act(obs, cfg.actors.eval_eps, rng)
            frame, r, _, over = env.step(a)
            obs = stacker.push(frame) if stacker else frame
            ep_ret += r
            t += 1
            print(f"t={t} a={a} r={r:+.1f} R={ep_ret:.1f}")
        print(json.dumps({"mode": "play", "steps": t, "return": ep_ret}))
        return 0

    return 2


def _build_solver(cfg, env):
    """Solver for eval/play: SequenceSolver for recurrent (r2d2) nets, the
    feed-forward Solver otherwise — a train-mode r2d2 checkpoint must be
    evaluable/playable from the CLI."""
    import numpy as np
    obs_dim = int(np.prod(env.obs_shape))
    if cfg.net.kind == "r2d2":
        from distributed_deep_q_tpu.parallel.sequence_learner import (
            SequenceSolver)
        return SequenceSolver(cfg, obs_dim=obs_dim)
    from distributed_deep_q_tpu.solver import Solver
    return Solver(cfg, obs_dim=obs_dim)


if __name__ == "__main__":
    sys.exit(main())
