"""Environments + actor-side helpers (SURVEY.md §1 L4, §2 "Actor / env" [M]).

The reference's ``game.py`` hosts ``AtariEnv`` (C++ ALE behind Python
bindings), ε-greedy action selection against the current Q-net, frame
preprocessing, and the actor loop that feeds transitions to replay over RPC
[M][R]. This module rebuilds that surface:

- ``GymEnv``   — gymnasium classic-control adapter (CartPole smoke, config 1).
- ``AtariEnv`` — ALE wrapper with the canonical DQN preprocessing stack
  (grayscale, 84×84 resize, frame-skip with 2-frame max, reward clip,
  terminal-on-life-loss, noop starts). Gated on ``ale_py`` being installed;
  actors are CPU-side by design (north star [M]) so nothing here touches JAX
  devices.
- ``FakeAtari`` — deterministic counter-frame env for byte-exact replay and
  pipeline tests without ALE (SURVEY §4 "dummy environments").
- ``NStepAccumulator`` — actor-side n-step transition composer for the
  explicit-transition replay path.

Truncation semantics: ``step`` returns ``(obs, reward, terminated,
episode_over)``; bootstrap discount is cut only on true termination, so
time-limit truncation (CartPole's 500-step cap) still bootstraps — required
for correct Q-values.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Protocol

import numpy as np

from distributed_deep_q_tpu.config import EnvConfig


class Env(Protocol):
    num_actions: int
    obs_shape: tuple[int, ...]
    obs_dtype: Any

    def reset(self) -> np.ndarray: ...
    def step(self, action: int) -> tuple[np.ndarray, float, bool, bool]: ...


class GymEnv:
    """Vector-observation gymnasium adapter (classic control)."""

    def __init__(self, env_id: str = "CartPole-v1", seed: int = 0,
                 reward_clip: float = 0.0):
        import gymnasium

        self._env = gymnasium.make(env_id)
        self._seed = seed
        self._n_resets = 0
        self._reward_clip = float(reward_clip)
        self.num_actions = int(self._env.action_space.n)
        self.obs_shape = tuple(self._env.observation_space.shape)
        self.obs_dtype = np.float32

    def reset(self) -> np.ndarray:
        obs, _ = self._env.reset(seed=self._seed + self._n_resets)
        self._n_resets += 1
        return np.asarray(obs, np.float32)

    def step(self, action: int):
        obs, reward, terminated, truncated, _ = self._env.step(int(action))
        reward = float(reward)
        if self._reward_clip > 0:
            reward = float(np.clip(reward, -self._reward_clip,
                                   self._reward_clip))
        return (np.asarray(obs, np.float32), reward,
                bool(terminated), bool(terminated or truncated))


class FakeAtari:
    """Deterministic frame env: pixel values count up with the step index.

    Episode length and rewards are fixed functions of the step counter, so
    replay contents are byte-predictable — used by the frame-stack boundary
    tests (SURVEY §4 "FakeAtari (counter frames)").
    """

    def __init__(self, episode_len: int = 10, num_actions: int = 4,
                 frame_shape: tuple[int, int] = (84, 84)):
        self.episode_len = episode_len
        self.num_actions = num_actions
        self.obs_shape = tuple(frame_shape)
        self.obs_dtype = np.uint8
        self._t = 0          # within-episode step
        self._global = 0     # global frame counter (mod 256)

    def _frame(self) -> np.ndarray:
        return np.full(self.obs_shape, self._global % 256, np.uint8)

    def reset(self) -> np.ndarray:
        self._t = 0
        self._global += 1
        return self._frame()

    def step(self, action: int):
        self._t += 1
        self._global += 1
        done = self._t >= self.episode_len
        reward = 1.0 if self._t % 3 == 0 else 0.0
        return self._frame(), reward, done, done


class SignalAtari:
    """Pixel env whose reward is a function of what's ON SCREEN — the
    learnability probe for the CNN + device-ring path.

    Each observation shows one bright band (out of ``num_actions`` bands;
    vertical or horizontal per ``orientation``) on a dark background; acting
    with the band's index pays +1, anything else 0, and a new band is drawn
    uniformly each step. Q*(s, a) = 1 for the shown band and γ·E[max Q]
    elsewhere — a contextual bandit: the policy must READ THE PIXELS to beat
    the 1/num_actions random-policy return, which is exactly what FakeAtari
    (counter frames, action-independent reward) cannot test. Orientation
    variants are distinct "games" for multi-game fleets (config 4).
    """

    def __init__(self, episode_len: int = 32, num_actions: int = 4,
                 frame_shape: tuple[int, int] = (84, 84), seed: int = 0,
                 orientation: str = "v"):
        assert orientation in ("v", "h")
        self.episode_len = int(episode_len)
        self.num_actions = int(num_actions)
        self.obs_shape = tuple(frame_shape)
        self.obs_dtype = np.uint8
        self.orientation = orientation
        self._rng = np.random.default_rng(seed)
        self._t = 0
        self._target = 0

    def _frame(self) -> np.ndarray:
        f = np.full(self.obs_shape, 20, np.uint8)
        h, w = self.obs_shape
        if self.orientation == "v":
            band = w // self.num_actions
            f[:, self._target * band:(self._target + 1) * band] = 220
        else:
            band = h // self.num_actions
            f[self._target * band:(self._target + 1) * band, :] = 220
        return f

    def reset(self) -> np.ndarray:
        self._t = 0
        self._target = int(self._rng.integers(self.num_actions))
        return self._frame()

    def step(self, action: int):
        self._t += 1
        reward = 1.0 if int(action) == self._target else 0.0
        self._target = int(self._rng.integers(self.num_actions))
        done = self._t >= self.episode_len
        return self._frame(), reward, done, done


class VelocitySignalAtari:
    """Pixel env whose reward is a function of MOTION, not appearance — the
    temporal-integration probe (VERDICT r3 next #9).

    One bright band drifts across the screen with a velocity drawn from
    ``num_actions`` distinct values; acting with the velocity's index pays
    +1. The band's POSITION is redrawn uniformly at every segment start,
    independent of the velocity, so a single frame carries zero reward
    information — Q* is constant over single frames. Beating random
    requires comparing at least two consecutive frames: the frame-stack
    path must read displacement across stack channels, and the stack=1
    recurrent path must carry the previous position in LSTM state. That is
    exactly the capability ``SignalAtari`` (static band ⇒ single-frame
    pattern matching) cannot test.

    Velocity changes every ``segment`` steps (with a fresh position), so
    ~1/segment of steps — plus the first step after reset, when the stack
    holds no prior same-segment frame — are unreadable even for a perfect
    decoder; the achievable ceiling is ≈ (1 - 1/segment) + 1/(segment·A)
    reward per step (~0.91 at segment=8, A=4) vs the 1/A = 0.25 random
    floor.

    Orientation "v": vertical band (spans all rows) drifting horizontally;
    "h": horizontal band drifting vertically — two distinct "games" for
    multi-game fleets, like SignalAtari's pair.
    """

    def __init__(self, episode_len: int = 32, num_actions: int = 4,
                 frame_shape: tuple[int, int] = (84, 84), seed: int = 0,
                 orientation: str = "v", segment: int = 8):
        """``segment=0`` holds the velocity for the WHOLE episode (only the
        reset redraws) — the easiest memory variant: read the motion once,
        carry the answer. Positive ``segment`` redraws velocity+position
        every that many steps."""
        assert orientation in ("v", "h")
        self.episode_len = int(episode_len)
        self.num_actions = int(num_actions)
        self.obs_shape = tuple(frame_shape)
        self.obs_dtype = np.uint8
        self.orientation = orientation
        self.segment = int(segment) if segment else self.episode_len + 1
        h, w = frame_shape
        self._axis = w if orientation == "v" else h
        self.band_width = max(3, self._axis // 8)
        # symmetric speeds, zero excluded (a parked band needs no temporal
        # integration to identify — it would reintroduce the single-frame
        # shortcut this env exists to remove): A=4 → (-2, -1, 1, 2) × u px
        u = max(2, self._axis // 16)
        half = self.num_actions // 2
        units = list(range(-half, 0)) + \
            list(range(1, self.num_actions - half + 1))
        self.velocities = tuple(int(u * m) for m in units)
        self._rng = np.random.default_rng(seed)
        self._t = 0
        self._v_idx = 0
        self._pos = 0

    def _redraw(self) -> None:
        self._v_idx = int(self._rng.integers(self.num_actions))
        self._pos = int(self._rng.integers(self._axis))

    def _frame(self) -> np.ndarray:
        f = np.full(self.obs_shape, 20, np.uint8)
        idx = (self._pos + np.arange(self.band_width)) % self._axis
        if self.orientation == "v":
            f[:, idx] = 220
        else:
            f[idx, :] = 220
        return f

    def reset(self) -> np.ndarray:
        self._t = 0
        self._redraw()
        return self._frame()

    def step(self, action: int):
        # reward keys on the velocity in effect over the frames the agent
        # just observed
        reward = 1.0 if int(action) == self._v_idx else 0.0
        self._t += 1
        if self._t % self.segment == 0:
            self._redraw()      # fresh velocity AND position: the new
            #                     position is independent of both the old
            #                     and new velocity, so boundary frames leak
            #                     nothing
        else:
            self._pos = (self._pos + self.velocities[self._v_idx]) \
                % self._axis
        done = self._t >= self.episode_len
        return self._frame(), reward, done, done


# ---------------------------------------------------------------------------
# Atari (ALE) with canonical DQN preprocessing
# ---------------------------------------------------------------------------


def _resize_area(img: np.ndarray, out_hw: tuple[int, int]) -> np.ndarray:
    """Bilinear-ish area resize in pure numpy (no cv2/PIL dependency).

    Matches the spirit of the canonical 84×84 downscale; exact interpolation
    kernel differences are irrelevant to learning but MUST stay fixed for
    eval comparability (SURVEY §7.3 item 5), so this is the one resize used
    everywhere (actors, eval, tests).
    """
    h, w = img.shape
    oh, ow = out_hw
    # integer-grid bilinear sampling at pixel centers
    ys = (np.arange(oh) + 0.5) * h / oh - 0.5
    xs = (np.arange(ow) + 0.5) * w / ow - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :]
    f = img.astype(np.float32)
    top = f[y0][:, x0] * (1 - wx) + f[y0][:, x1] * wx
    bot = f[y1][:, x0] * (1 - wx) + f[y1][:, x1] * wx
    return ((1 - wy) * top + wy * bot).astype(np.uint8)


class AtariEnv:
    """ALE-backed Atari with Nature-DQN preprocessing (SURVEY §3.3 [M][P]).

    Preprocessing constants are the community-standard ones (frame_skip=4,
    max over the last 2 raw frames, 84×84 grayscale, reward clip ±1,
    terminal-on-life-loss, ≤30 random noops at reset); they are encoded in
    ``EnvConfig`` and tested as constants.
    """

    def __init__(self, cfg: EnvConfig, seed: int = 0, env=None):
        """``env`` injects a pre-built gymnasium-compatible raw env (RGB
        frames + ``lives`` info) — the test seam that lets the whole
        preprocessing stack execute without ALE installed."""
        if env is None:
            try:
                import ale_py  # noqa: F401
                import gymnasium
            except ImportError as e:  # pragma: no cover - needs ALE absent
                raise ImportError(
                    "AtariEnv requires ale_py (not installed in this image); "
                    "use FakeAtari for tests or install ale-py on actor hosts"
                ) from e
            kwargs = ({"full_action_space": True}
                      if cfg.full_action_space else {})
            env = gymnasium.make(cfg.id, frameskip=1,
                                 repeat_action_probability=0.0, **kwargs)
        self.cfg = cfg
        self._env = env
        self._seed = seed
        self._n_resets = 0
        self._rng = np.random.default_rng(seed)
        self.num_actions = int(self._env.action_space.n)
        self.obs_shape = tuple(cfg.frame_shape)
        self.obs_dtype = np.uint8
        self._lives = 0
        self._steps = 0
        self._raw = deque(maxlen=2)

    def _observe(self) -> np.ndarray:
        maxed = np.max(np.stack(self._raw), axis=0) if len(self._raw) > 1 \
            else self._raw[-1]
        gray = (0.299 * maxed[..., 0] + 0.587 * maxed[..., 1]
                + 0.114 * maxed[..., 2]).astype(np.uint8)
        return _resize_area(gray, self.cfg.frame_shape)

    def reset(self) -> np.ndarray:
        obs, info = self._env.reset(seed=self._seed + self._n_resets)
        self._n_resets += 1
        self._steps = 0
        self._raw.clear()
        self._raw.append(obs)
        for _ in range(int(self._rng.integers(1, self.cfg.noop_max + 1))):
            obs, _, term, trunc, info = self._env.step(0)
            self._raw.append(obs)
            if term or trunc:
                obs, info = self._env.reset()
                self._raw.clear()
                self._raw.append(obs)
        self._lives = info.get("lives", 0)
        return self._observe()

    def step(self, action: int):
        total = 0.0
        terminated = truncated = False
        for _ in range(self.cfg.frame_skip):
            obs, r, terminated, truncated, info = self._env.step(int(action))
            self._raw.append(obs)
            total += float(r)
            if terminated or truncated:
                break
        life_lost = False
        if self.cfg.terminal_on_life_loss:
            lives = info.get("lives", self._lives)
            life_lost = 0 < lives < self._lives
            self._lives = lives
        if self.cfg.reward_clip > 0:
            total = float(np.clip(total, -self.cfg.reward_clip,
                                  self.cfg.reward_clip))
        # the standard Atari 30-minute cap (108k raw frames = 27k agent
        # steps at skip 4): a TIME-LIMIT truncation — bootstrap intact
        # (done stays False), episode over (EVAL_PROTOCOL.md; binds both
        # training and eval because it lives in the env)
        self._steps += 1
        if self.cfg.max_episode_steps > 0 \
                and self._steps >= self.cfg.max_episode_steps:
            truncated = True
        done = terminated or life_lost          # cuts bootstrap
        over = terminated or truncated          # needs env.reset()
        return self._observe(), total, done, over


class StepLatencyEnv:
    """Transparent env wrapper timing each ``step()`` call (wall ms).

    The remote actor loops drain the buffer into the ``tm_env_step_ms``
    telemetry channel on every transition flush, giving the learner-side
    ``fleet/env_step_ms`` histogram its samples. The buffer is bounded so
    an actor that stops flushing (server gone, long episode) cannot grow
    it without limit — old samples fall off, which is the right bias for
    a latency distribution. Everything else delegates to the wrapped env.
    """

    def __init__(self, env: Env, maxlen: int = 512):
        self._env = env
        self._step_ms: deque = deque(maxlen=maxlen)

    def step(self, action: int):
        t0 = time.perf_counter()
        out = self._env.step(action)
        self._step_ms.append(1e3 * (time.perf_counter() - t0))
        return out

    def reset(self) -> np.ndarray:
        return self._env.reset()

    def drain_step_ms(self) -> list[float]:
        out = list(self._step_ms)
        self._step_ms.clear()
        return out

    def __getattr__(self, name: str):
        return getattr(self._env, name)


def make_env(cfg: EnvConfig, seed: int = 0) -> Env:
    if cfg.kind == "gym":
        return GymEnv(cfg.id, seed, reward_clip=cfg.reward_clip)
    if cfg.kind == "atari":
        return AtariEnv(cfg, seed)
    if cfg.kind == "fake_atari":
        return FakeAtari(frame_shape=cfg.frame_shape)
    if cfg.kind == "signal_atari":
        # id "signal" = vertical bands, "signal-h" = horizontal — two
        # distinct fake "games" for multi-game fleet tests; the "-vel"
        # ids select the moving-band temporal-integration variant
        orientation = "h" if cfg.id.endswith("-h") else "v"
        if "-vel" in cfg.id:
            # "-ep" holds velocity for the whole episode (memory-gate
            # difficulty tier); default redraws every 8 steps
            return VelocitySignalAtari(frame_shape=cfg.frame_shape,
                                       seed=seed, orientation=orientation,
                                       segment=0 if "-ep" in cfg.id else 8)
        return SignalAtari(frame_shape=cfg.frame_shape, seed=seed,
                           orientation=orientation)
    raise ValueError(f"unknown env kind {cfg.kind!r}")


def make_envs(cfgs, seeds) -> list[Env]:
    """Vector-aware ``make_env``: one env per (cfg, seed) row.

    ``cfgs`` is one EnvConfig (replicated across rows) or a per-row
    sequence (multi-game fleets pass ``env_for_actor`` output per
    global id). This is the seam ``actors/vector.py`` stacks behind a
    ``VectorEnv`` — building rows HERE keeps the per-row seeding
    discipline identical to the per-process fleet, which is what the
    bitwise-parity guarantee rides on. Telemetry wrappers go AROUND
    the vector (``VectorStepLatencyEnv``), never around row 0.
    """
    if not isinstance(cfgs, (list, tuple)):
        cfgs = [cfgs] * len(seeds)
    if len(cfgs) != len(seeds):
        raise ValueError(f"{len(cfgs)} env configs vs {len(seeds)} seeds")
    return [make_env(c, seed=int(s)) for c, s in zip(cfgs, seeds)]


class FrameStacker:
    """Maintains the rolling [H, W, stack] uint8 observation for pixel envs.

    One implementation shared by the training loop, eval, play, and remote
    actors, so stack semantics (zero-fill at episode start, newest frame in
    the last channel) can never drift between them.
    """

    def __init__(self, frame_shape: tuple[int, int], stack: int):
        self._buf = np.zeros(tuple(frame_shape) + (stack,), np.uint8)

    def reset(self, frame: np.ndarray) -> np.ndarray:
        self._buf[:] = 0
        self._buf[..., -1] = frame
        return self._buf

    def push(self, frame: np.ndarray) -> np.ndarray:
        self._buf = np.roll(self._buf, -1, axis=-1)
        self._buf[..., -1] = frame
        return self._buf

    @property
    def obs(self) -> np.ndarray:
        return self._buf


# ---------------------------------------------------------------------------
# Actor-side n-step composition (explicit-transition replay path)
# ---------------------------------------------------------------------------


class NStepAccumulator:
    """Rolls (s, a, r) history into n-step transitions at the actor.

    Emits (obs, action, R_n, next_obs, discount) where R_n = Σ γᵏ r and
    discount = γⁿ·(1-done); on episode end, flushes the partial tail with
    the remaining horizon. Keeps the replay server storage-agnostic about n.
    """

    def __init__(self, n_step: int, gamma: float):
        self.n = int(n_step)
        self.gamma = float(gamma)
        self._buf: deque = deque()

    def push(self, obs, action, reward, next_obs, done: bool):
        """Returns a list of matured transitions (possibly empty)."""
        out = []
        self._buf.append([obs, action, reward])
        if len(self._buf) >= self.n:
            out.append(self._compose(next_obs, done))
            self._buf.popleft()
        if done:
            while self._buf:
                out.append(self._compose(next_obs, True))
                self._buf.popleft()
        return out

    def flush_truncated(self, next_obs):
        """Flush the buffered tail at a time-limit truncation.

        Unlike episode termination, truncation keeps the bootstrap: each
        emitted transition gets discount γᵏ over its (shortened) horizon
        with ``next_obs`` = the final observed state.
        """
        out = []
        while self._buf:
            out.append(self._compose(next_obs, False))
            self._buf.popleft()
        return out

    def _compose(self, next_obs, done: bool):
        r, g = 0.0, 1.0
        for _, _, rew in self._buf:
            r += g * rew
            g *= self.gamma
        obs, action, _ = self._buf[0]
        return (obs, action, np.float32(r), next_obs,
                np.float32(0.0 if done else g))

    def reset(self) -> None:
        self._buf.clear()
