from distributed_deep_q_tpu.actors.game import (  # noqa: F401
    GymEnv,
    FakeAtari,
    NStepAccumulator,
    make_env,
)
