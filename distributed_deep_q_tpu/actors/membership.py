"""Elastic fleet membership — live host join/leave with shard handoff.

ROADMAP item 4 names the gap this closes: `actors/assignment.py`
guarantees minimal remap on host churn, but the host SET itself was
fixed at boot. This module makes membership a first-class, runtime
object:

``MembershipRegistry``
    An epoch-numbered host set served over the existing v4 CRC wire.
    The registry rides inside one ``ReplayFeedServer`` (the seed host
    attaches it via ``attach_membership``) and answers four verbs —
    ``fleet_join`` / ``fleet_leave`` / ``fleet_lease`` / ``fleet_view``
    — so any host or actor can observe and mutate the fleet with the
    same resilient client it already holds. Every membership change
    bumps the epoch; actors watch the epoch and re-run
    ``assign_fleet``/``owner_host`` against the new token set.

Liveness is LEASE-based, deliberately distinct from the per-actor
heartbeats: a heartbeat says "this actor thread is alive", a lease says
"this HOST is still a legitimate shard owner". A host that stops
renewing past ``lease_s`` is expired by ``expire()`` — same epoch bump
as a voluntary leave, so the actor-side remap path is identical.

Shard handoff (the departing-host protocol) reuses the PR 6 durability
plane end to end:

- export: ``export_shard`` drains the departing server and snapshots
  through ``GenerationStore`` — payload files first, ``MANIFEST.json``
  last, so the handoff commit point is atomic. The snapshot carries the
  replay rows, the PER tree/RNG state, AND the ``(actor_id, flush_seq)``
  dedup map.
- import: ``import_shard`` warm-boots a fresh ``ReplayFeedServer`` from
  that store. A torn handoff (crash mid-export) fails CRC verification,
  is quarantined, and the importer falls back to the previous good
  generation — never a half-shard.

Exactly-once through the remap: an actor's un-acked in-flight flush may
have LANDED on the departed host before the ack was lost. Its stamp is
inside the exported dedup map, so a resend to the IMPORTER dedups
server-side. For the one remaining hole — the actor remaps to a host
that is NOT the importer — ``resend_floor`` asks the importer (found
via the registry's departed→importer lineage) for the actor's highest
landed seq; the resilient client skips any resend at or below that
floor (``ResilientReplayFeedClient.resend_floor``).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any

FLEET_METHODS = ("fleet_join", "fleet_leave", "fleet_lease", "fleet_view")

DEFAULT_LEASE_S = 30.0


class MembershipRegistry:
    """Epoch-numbered fleet host set with lease-based liveness.

    Thread-safe: every field moves under ``_fleet_lock`` (serve threads
    answering fleet verbs race the supervisor's gauge reads and the
    lease sweeper).
    """

    def __init__(self, lease_s: float = DEFAULT_LEASE_S):
        self._fleet_lock = threading.Lock()
        # token → {"host": str, "port": int, "lease": monotonic deadline}
        self._fleet_members: dict[str, dict[str, Any]] = {}
        self._fleet_epoch = 0
        # departed token → importing token (shard lineage for resend_floor)
        self._fleet_lineage: dict[str, str] = {}
        self._fleet_stats = {"joins": 0, "leaves": 0,
                             "lease_expired": 0, "handoffs": 0}
        self.lease_s = float(lease_s)

    # -- membership verbs ---------------------------------------------------

    def join(self, token: str, host: str, port: int) -> int:
        """Admit (or re-address) a host; returns the new epoch.

        Tokens are the stable hash-ring identities from
        ``assignment.host_tokens`` — re-joining with a new address is a
        reconnect, not a remap (the ring never sees the address)."""
        if not token:
            raise ValueError("membership token must be non-empty")
        now = time.monotonic()
        with self._fleet_lock:
            self._fleet_members[token] = {
                "host": str(host), "port": int(port),
                "lease": now + self.lease_s,
            }
            # a re-join supersedes any departed-lineage entry: the token
            # owns its shard again, floors resolve against it directly
            self._fleet_lineage.pop(token, None)
            self._fleet_epoch += 1
            self._fleet_stats["joins"] += 1
            return self._fleet_epoch

    def leave(self, token: str, importer: str = "") -> int:
        """Retire a host; returns the new epoch.

        ``importer`` names the token that imported the departing host's
        replay shard (may be empty for a shard-less drain). The lineage
        entry lets remapped actors resolve their resend floor against
        whoever actually holds their landed flushes."""
        with self._fleet_lock:
            self._fleet_members.pop(token, None)
            if importer:
                self._fleet_lineage[token] = str(importer)
                self._fleet_stats["handoffs"] += 1
            self._fleet_epoch += 1
            self._fleet_stats["leaves"] += 1
            return self._fleet_epoch

    def renew(self, token: str) -> bool:
        """Extend a member's lease; False if the token is not a member
        (expired or never joined — the caller should re-join)."""
        with self._fleet_lock:
            entry = self._fleet_members.get(token)
            if entry is None:
                return False
            entry["lease"] = time.monotonic() + self.lease_s
            return True

    def expire(self, now: float | None = None) -> tuple[str, ...]:
        """Sweep lapsed leases; returns the expired tokens. Each
        expiry bumps the epoch exactly like a voluntary leave (no
        importer — the shard is recovered out of band)."""
        now = time.monotonic() if now is None else now
        with self._fleet_lock:
            lapsed = tuple(t for t, e in self._fleet_members.items()
                           if e["lease"] < now)
            for token in lapsed:
                self._fleet_members.pop(token, None)
                self._fleet_epoch += 1
                self._fleet_stats["lease_expired"] += 1
            return lapsed

    def epoch(self) -> int:
        with self._fleet_lock:
            return self._fleet_epoch

    def view(self) -> dict[str, Any]:
        """Flat wire-friendly snapshot: epoch + member table + lineage.

        Nested data rides as JSON strings (the ``findings_json``
        precedent from the health plane — the v4 wire stays a flat
        scalar/bytes dict, no format version bump)."""
        with self._fleet_lock:
            members = {t: [e["host"], e["port"]]
                       for t, e in self._fleet_members.items()}
            return {
                "ok": True,
                "epoch": self._fleet_epoch,
                "members_json": json.dumps(members, sort_keys=True),
                "lineage_json": json.dumps(self._fleet_lineage,
                                           sort_keys=True),
            }

    # -- wire dispatch (delegated from ReplayFeedServer._dispatch) ----------

    def _dispatch(self, req: dict[str, Any]) -> dict[str, Any]:
        method = req.get("method")
        if method == "fleet_join":
            epoch = self.join(str(req.get("token", "")),
                              str(req.get("host", "")),
                              int(req.get("port", 0)))
            return {"ok": True, "epoch": epoch}
        if method == "fleet_leave":
            epoch = self.leave(str(req.get("token", "")),
                               importer=str(req.get("importer", "")))
            return {"ok": True, "epoch": epoch}
        if method == "fleet_lease":
            ok = self.renew(str(req.get("token", "")))
            return {"ok": ok, "epoch": self.epoch()}
        if method == "fleet_view":
            return self.view()
        return {"error": f"unknown fleet method {method!r}"}

    def gauges(self) -> dict[str, float]:
        """``fleet/*`` gauges for the supervisor's metrics tick."""
        with self._fleet_lock:
            return {
                "fleet/epoch": float(self._fleet_epoch),
                "fleet/members": float(len(self._fleet_members)),
                "fleet/joins": float(self._fleet_stats["joins"]),
                "fleet/leaves": float(self._fleet_stats["leaves"]),
                "fleet/lease_expired":
                    float(self._fleet_stats["lease_expired"]),
                "fleet/handoffs": float(self._fleet_stats["handoffs"]),
            }


# -- view helpers (client side) ----------------------------------------------


def view_tokens(view: dict[str, Any]) -> tuple[str, ...]:
    """Sorted member tokens from a ``fleet_view`` reply — the exact
    host tuple to feed ``assign_fleet`` (sorted so every observer of
    the same epoch computes the same assignment)."""
    return tuple(sorted(json.loads(view["members_json"])))


def view_address(view: dict[str, Any], token: str) -> tuple[str, int]:
    """(host, port) for a member token in a ``fleet_view`` reply."""
    host, port = json.loads(view["members_json"])[token]
    return str(host), int(port)


def resolve_importer(view: dict[str, Any], token: str) -> str:
    """Follow the departed→importer lineage transitively: the member
    that currently holds ``token``'s shard (may be ``token`` itself if
    it never left, or "" if the chain dead-ends outside the fleet)."""
    members = json.loads(view["members_json"])
    lineage = json.loads(view["lineage_json"])
    seen: set[str] = set()
    cur = token
    while cur not in members:
        if cur in seen or cur not in lineage:
            return ""
        seen.add(cur)
        cur = lineage[cur]
    return cur


def resend_floor(host: str, port: int, actor_id: int,
                 timeout: float = 10.0) -> int:
    """Ask a server for ``actor_id``'s highest landed flush_seq.

    Called during a remap, BEFORE releasing the actor's in-flight retry
    to its new owner: if the floor covers the in-flight seq, the flush
    already landed on the departed host (and traveled inside the
    exported shard) — the resilient client skips the resend instead of
    double-inserting."""
    from distributed_deep_q_tpu.rpc.replay_server import ReplayFeedClient

    client = ReplayFeedClient(host, port, actor_id=actor_id,
                              timeout=timeout)
    try:
        reply = client.call("stream_seq")
        return int(reply.get("seq", -1))
    finally:
        client.close()


# -- shard handoff (GenerationStore round trip) ------------------------------


def export_shard(server, path: str,
                 drain_timeout: float = 5.0) -> dict[str, Any]:
    """Gracefully retire a server, exporting its replay shard.

    ``shutdown`` closes the listener, drains in-flight dispatches to
    zero, then snapshots through ``GenerationStore`` — payload files
    first, manifest last, so the handoff either committed completely or
    (torn) fails CRC at import and falls back. Returns the handoff
    receipt the churn gate and PERF bench consume."""
    t0 = time.perf_counter()
    with server.replay_lock:
        rows = len(server.replay) if server.replay is not None else 0
    server.shutdown(path, drain_timeout=drain_timeout)
    return {
        "rows": int(rows),
        "export_ms": (time.perf_counter() - t0) * 1e3,
        "path": path,
    }


def import_shard(replay, path: str, host: str = "127.0.0.1",
                 port: int = 0, flow=None,
                 snapshot_keep: int = 3) -> tuple[Any, dict[str, Any]]:
    """Warm-boot a fresh server from an exported shard.

    The generational restore runs before the listener opens (so no
    actor ever sees a half-restored dedup map), quarantining any torn
    generation and falling back to the previous good one. Returns
    ``(server, receipt)``; ``receipt["generation"]`` is -1 when nothing
    restorable was found (fresh-empty fallback)."""
    from distributed_deep_q_tpu.rpc.replay_server import ReplayFeedServer

    t0 = time.perf_counter()
    server = ReplayFeedServer(replay, host=host, port=port,
                              snapshot_path=path, flow=flow,
                              snapshot_keep=snapshot_keep)
    with server.replay_lock:
        rows = len(server.replay) if server.replay is not None else 0
    return server, {
        "rows": int(rows),
        "import_ms": (time.perf_counter() - t0) * 1e3,
        "generation": int(server._restored_generation),
        "path": path,
    }
