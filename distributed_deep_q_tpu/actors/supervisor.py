"""Actor fleet + distributed training topology (SURVEY.md §1 L5, §7.2 step 3).

Process shape (rebuilt from the reference's Spark-driver/worker layout [M]):
one learner process (this module's ``train_distributed``) hosting the TPU
mesh, the replay buffer, and the in-process ``ReplayFeed`` RPC service;
N CPU actor *processes* (``actor_main``) each running env + ε-greedy policy
against a locally-pulled θ, pushing transition chunks over the RPC boundary.
The supervisor thread gives the failure-detection capability (SURVEY §5.3):
actors are stateless, so a dead/hung actor (process exit or heartbeat
silence) is simply restarted.

Ape-X ε ladder: actor i uses ε_i = base^(1 + i·α/(N-1)) — a fixed spread of
exploration rates across the fleet (Horgan et al. 2018) replacing the
single-actor annealed schedule.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import os
import threading
import time
from collections import deque
from typing import Any

import numpy as np

from distributed_deep_q_tpu import health, tracing
from distributed_deep_q_tpu.config import Config
from distributed_deep_q_tpu.metrics import Metrics


def actor_epsilon(i: int, n: int, base: float, alpha: float) -> float:
    if n <= 1:
        return base
    return float(base ** (1.0 + i * alpha / (n - 1)))


def _probe_envs(cfg: Config):
    """Probe every configured game once: verifies the fleet shares ONE
    action space (a single Q-head serves all games — config 4's multi-game
    mode needs ``env.full_action_space`` for ALE) and returns the first
    game's probe env for shape/dtype discovery."""
    from distributed_deep_q_tpu.actors.game import make_env
    from distributed_deep_q_tpu.config import env_for_actor

    games = cfg.env.games or (cfg.env.id,)
    counts: dict[str, int] = {}
    first = None
    for i, g in enumerate(games):
        e = make_env(env_for_actor(cfg.env, i), seed=cfg.train.seed)
        if first is None:
            first = e
        counts[g] = e.num_actions
        if e is not first:
            # probe envs beyond the first exist only for their action
            # count — close them (8 live ALE emulators at the apex preset
            # would otherwise leak until GC)
            close = getattr(e, "close", None)
            if close:
                close()
    if len(set(counts.values())) != 1:
        raise ValueError(
            f"multi-game fleet requires one shared action space, got "
            f"{counts}; set env.full_action_space=true for ALE games")
    return first


def _split_fleet_across_processes(cfg: Config, pixel: bool, metrics,
                                  ring_desc: str, fused_ok: bool = False):
    """Config 5 FULL shape (SURVEY §7.3 item 6): every learner process runs
    its own ReplayFeed server + actor slice + replay shard; each samples
    its batch/pc local rows into the train step, whose pmean spans hosts
    (train_step → global_batch). No data plane crosses hosts outside the
    step — actor RPC fans into the local host only, shards never overlap
    (dedup-free sampling). Local actor ids 0..k-1 double as the host's
    replay streams; global identity (ε ladder / env seeds / multi-game
    assignment) comes from the offset. ``ring_desc`` names the
    single-controller device ring in the rejection message.

    Returns (cfg, local_batch, metrics, pc, pid) — metrics swapped to a
    sink-less instance on non-zero processes (file/TB sinks live on
    process 0 only).
    """
    import dataclasses

    import jax

    pc, pid = jax.process_count(), jax.process_index()
    local_batch = cfg.replay.batch_size
    if pc > 1:
        if cfg.replay.batch_size % pc:
            raise ValueError(f"replay.batch_size={cfg.replay.batch_size} "
                             f"must divide across {pc} processes")
        if cfg.actors.num_actors % pc:
            raise ValueError(f"actors.num_actors={cfg.actors.num_actors} "
                             f"must divide across {pc} processes")
        if pixel and cfg.replay.device_resident and not (
                fused_ok and cfg.replay.prioritized
                and cfg.replay.device_per):
            hint = ("the FUSED ring (replay.prioritized=true + "
                    "replay.device_per=true — per-host staging into the "
                    "global mesh ring, lockstep flush) or " if fused_ok
                    else "")
            raise ValueError(
                f"the {ring_desc}'s host-sampled path is "
                f"single-controller; multi-host --distributed pixel runs "
                f"need either {hint}replay.device_resident=false "
                "(per-host host-RAM shards feeding global_batch)")
        local_batch = cfg.replay.batch_size // pc
        k = cfg.actors.num_actors // pc
        if cfg.actors.assignment == "hash":
            # consistent-hash placement (actors/assignment.py): each host
            # owns the gids the bounded-load ring assigns its TOKEN, so a
            # restarting actor keeps its host, host join/leave remaps only
            # ~fleet/pc actors, and an address change is just a reconnect.
            # Fleet % pc == 0 (checked above) makes the slices exactly k
            # long, so per-host replay geometry stays uniform.
            from distributed_deep_q_tpu.actors.assignment import local_slice
            gids = local_slice(cfg.actors.num_actors, pc, pid)
            cfg = cfg.replace(actors=dataclasses.replace(
                cfg.actors, num_actors=k, actor_id_offset=0,
                actor_gids=tuple(gids),
                fleet_size=cfg.actors.num_actors))
        else:
            cfg = cfg.replace(actors=dataclasses.replace(
                cfg.actors, num_actors=k, actor_id_offset=pid * k,
                fleet_size=cfg.actors.num_actors))
        if pid != 0:
            metrics = Metrics()
    return cfg, local_batch, metrics, pc, pid


class _ActorComms:
    """θ-pull + liveness policy, shared by both actor loop bodies.

    Heartbeats run on their OWN daemon thread, so liveness is independent
    of the env loop: a single ``env.step()`` (or a blocking RPC) stalling
    longer than the supervisor's ``heartbeat_timeout`` must not get a
    healthy actor respawned — the beat keeps flowing while the loop is
    stuck. The beat is PROGRESS-AWARE, not unconditional: once the loop's
    watermark (advanced by ``maybe_pull``, called every iteration) is
    older than ``actors.env_stall_budget``, beating stops, so a
    permanently wedged env still goes silent and gets replaced — the
    budget is what separates "slow step" from "hung". The client stub is
    thread-safe (one lock serializes wire frames). θ pulls stay ON the
    env loop — they install weights into the qnet the loop is reading —
    and are phase-jittered per actor so a fleet never pulls in lockstep
    (VERDICT r3 weak #6).
    """

    # satellite telemetry/alerting knobs (class-level so tests can tune):
    # after HB_WARN_AFTER consecutive heartbeat failures, log a warning at
    # most every HB_WARN_PERIOD seconds — backoff alone is silent, and a
    # fleet quietly riding data traffic is exactly what r4 asked to surface
    HB_WARN_AFTER = 8
    HB_WARN_PERIOD = 30.0

    def __init__(self, cfg: Config, client, qnet, rng):
        self._client = client
        self._qnet = qnet
        self._period = max(cfg.actors.param_sync_period, 1)
        self._phase = int(rng.integers(self._period))
        self._version = -1
        # telemetry buffers, drained into tm_* arrays on each transition
        # flush (bounded: a stalled flush must not grow them unboundedly);
        # appended from the env loop (_pull_ms) and the beat thread
        # (_hb_ms) — deque ops are atomic under the GIL
        self._pull_ms: deque = deque(maxlen=64)
        self._hb_ms: deque = deque(maxlen=64)
        self._hb_failures = 0
        self._hb_last_warn = 0.0
        # the beat paces on a PROCESS-LOCAL event, never on the shared
        # multiprocessing stop event: a thread parked in mp.Event.wait()
        # registers as a sleeper on the event's shared Condition, and a
        # SIGKILL'd actor (fault injection, OOM kill) dies still
        # registered — the supervisor's next stop_event.set() then blocks
        # forever in notify_all() waiting for the dead sleeper's ack.
        # The daemon thread dies with the process; clean exits call
        # close() from the loop's finally.
        self._local_stop = threading.Event()
        self._stall_budget = float(cfg.actors.env_stall_budget)
        self._watermark = time.monotonic()
        # staleness guard (ISSUE 5): the newest published θ version rides
        # back on every flush reply (note_published); once the pulled
        # version trails it by more than max_param_lag, the next
        # maybe_pull blocks on a fresh pull regardless of the period
        self._max_lag = int(getattr(cfg.actors, "max_param_lag", 0))
        self._published = -1
        self.lag_blocks = 0  # pulls forced by the staleness guard
        hb = cfg.actors.heartbeat_period
        if hb:
            threading.Thread(target=self._beat, args=(float(hb),),
                             name="actor-heartbeat", daemon=True).start()

    def _beat(self, period: float) -> None:
        # transient-failure policy (VERDICT r4 weak #5 / ADVICE): a network
        # hiccup must NOT kill the beat thread permanently — a healthy but
        # idle actor would then ride on data traffic alone and get respawned
        # mid-episode, the exact event this thread exists to prevent. Retry
        # with exponential backoff while the loop is alive; only a
        # non-network error ends the thread, loudly.
        #
        # single-attempt sends: the beat's period IS its retry cadence —
        # the resilient client's internal retry loop would hold the beat
        # hostage for a full deadline and defeat the stall-budget gate
        call = getattr(self._client, "call_once", self._client.call)
        backoff = period
        while not self._local_stop.wait(backoff):
            if (self._stall_budget
                    and time.monotonic() - self._watermark
                    > self._stall_budget):
                backoff = period
                continue  # loop wedged past the budget: go silent (the
                #           supervisor respawns); resume if it recovers
            try:
                t0 = time.perf_counter()
                call("heartbeat")
                self._hb_ms.append(1e3 * (time.perf_counter() - t0))
                self._hb_failures = 0
                backoff = period
            except (ConnectionError, OSError, ValueError):
                # server gone, mid-restart, or stream desync (recv_msg
                # raises ValueError on a bad frame; the client already
                # dropped the socket so the next call reconnects): back
                # off (cap ~8×period) and keep trying — the env loop
                # discovers a dead learner on its own wire calls
                backoff = min(backoff * 2, period * 8)
                self._hb_failures += 1
                now = time.monotonic()
                if (self._hb_failures >= self.HB_WARN_AFTER
                        and now - self._hb_last_warn > self.HB_WARN_PERIOD):
                    self._hb_last_warn = now
                    logging.getLogger(__name__).warning(
                        "heartbeat: %d consecutive failures (server "
                        "unreachable?); retrying every %.1fs",
                        self._hb_failures, backoff)
            except Exception as e:  # noqa: BLE001 — protocol desync etc.
                logging.getLogger(__name__).warning(
                    "heartbeat thread exiting on %s: %s",
                    type(e).__name__, e)
                return

    def close(self) -> None:
        self._local_stop.set()

    def touch(self) -> None:
        """Advance the liveness watermark for INTENTIONAL waits — the
        resilient client calls this while pacing to credits or waiting
        out a SHED, so a backpressured actor reads as alive, not hung."""
        self._watermark = time.monotonic()

    def note_published(self, version) -> None:
        """Record the newest θ version the server advertised on a flush
        reply (env-loop only; plain store, no lock needed)."""
        if version is not None and int(version) > self._published:
            self._published = int(version)

    def stale(self) -> bool:
        """True when the pulled θ trails the published version by more
        than ``actors.max_param_lag`` — the actor must not act again
        until a fresh pull lands (bounded staleness, IMPACT-style)."""
        return (self._max_lag > 0 and self._version >= 0
                and self._published - self._version > self._max_lag)

    def maybe_pull(self, steps: int) -> None:
        self._watermark = time.monotonic()  # loop progress (beat gate)
        due = steps == 0 or (steps + self._phase) % self._period == 0
        stale = self.stale()
        if not (due or stale):
            return
        if stale and not due:
            self.lag_blocks += 1
        t0 = time.perf_counter()
        with tracing.span("param_pull"):
            version, weights = self._client.get_params(
                have_version=self._version)
            # time the full round trip incl. installing fresh weights —
            # that is the latency the env loop actually pays
            if weights is not None:
                self._qnet.set_weights(weights)
                self._version = version
        self._pull_ms.append(1e3 * (time.perf_counter() - t0))

    def drain_telemetry(self) -> dict[str, np.ndarray]:
        """Buffered latency samples as ``tm_*`` wire arrays (cleared on
        read); the server folds them into its fleet histograms."""
        out: dict[str, np.ndarray] = {}
        for key, q in (("tm_param_pull_ms", self._pull_ms),
                       ("tm_heartbeat_rtt_ms", self._hb_ms)):
            if q:
                samples = [q.popleft() for _ in range(len(q))]
                out[key] = np.asarray(samples, np.float32)
        return out


class _RemoteInference:
    """Exploit-action source for ``remote_inference`` mode (ISSUE 9): the
    actor ships observations to the ``InferenceServer`` and receives
    argmax actions — zero steady-state param pulls, staleness eliminated
    by construction (every action is computed against the server's live
    θ). ε-greedy stays OUT of this class, on the actor's own seeded rng,
    so the exploration stream is bitwise identical to local inference.

    Transport rides the resilient wrapper (reconnect/backoff, credit
    grants feed its token bucket) and honors explicit shed replies with
    the server's retry hint. An infer is a pure function of (θ, obs), so
    a re-send after a shed or an ambiguous transport failure is
    idempotent for free — no flush_seq machinery needed."""

    def __init__(self, cfg: Config, stop_event, actor_id: int, gid: int,
                 touch=None):
        from distributed_deep_q_tpu.rpc.inference_server import \
            InferenceClient
        from distributed_deep_q_tpu.rpc.resilience import (
            ResilientReplayFeedClient, RetryPolicy)

        policy = RetryPolicy(base_delay=cfg.actors.rpc_retry_base,
                             max_delay=cfg.actors.rpc_retry_max,
                             deadline=cfg.actors.rpc_retry_deadline)
        # retries on the INITIAL connect too: the inference server comes
        # up with the rest of the learner plane, maybe after this child
        seed = cfg.train.seed + 60217 * (gid + 1)
        rng = np.random.default_rng(seed)
        stub = policy.run(
            lambda: InferenceClient(cfg.inference.host, cfg.inference.port,
                                    actor_id=actor_id,
                                    timeout=cfg.actors.rpc_call_timeout),
            rng=rng, should_abort=stop_event.is_set)
        self._client = ResilientReplayFeedClient(
            stub, policy, should_abort=stop_event.is_set, seed=seed)
        self._client.on_backpressure = touch
        self._rng = rng
        self._seq = 0
        self.version = -1
        self.sheds = 0

    def action(self, obs) -> int:
        """One remote argmax action for a single observation."""
        return int(self.actions(np.asarray(obs)[None])[0])

    def actions(self, obs) -> np.ndarray:
        """Batched remote argmax actions: ONE ``infer`` RPC for a whole
        row batch — the vector actor's one-RPC-per-wall-tick path. A
        shed sheds the WHOLE batch (the server admits whole requests
        only), so retry keeps the rows together and row order is
        preserved end to end."""
        batch = np.ascontiguousarray(np.asarray(obs))
        seq = self._seq
        self._seq += 1
        while True:
            with tracing.span("rpc_call"):
                resp = self._client.call("infer", obs=batch, seq=seq)
            if resp.get("error"):
                from distributed_deep_q_tpu.rpc.resilience import RPCError
                raise RPCError(f"infer rejected: {resp['error']}")
            if resp.get("shed"):
                self.sheds += 1
                tracing.instant(
                    "shed", plane="inference",
                    retry_after_ms=float(resp.get("retry_after_ms", 0)))
                delay = max(float(resp.get("retry_after_ms", 100)),
                            10.0) / 1e3
                # decorrelate the fleet's re-sends a little
                delay *= 1.0 + 0.25 * float(self._rng.random())
                self._client._sleep_backpressure(delay)
                continue
            self._client._note_reply(resp)
            if resp.get("version") is not None:
                self.version = int(resp["version"])
            return np.asarray(resp["actions"]).astype(np.int64)

    def close(self) -> None:
        self._client.close()


# ---------------------------------------------------------------------------
# Actor process
# ---------------------------------------------------------------------------


def actor_main(cfg: Config, host: str, port: int, actor_id: int,
               stop_event, max_env_steps: int = 0) -> None:
    """One CPU actor: play with ε-greedy policy, ship transitions, pull θ.

    Runs in a spawned process with JAX pinned to CPU (actors never touch the
    accelerator — north star [M]). All communication goes through the
    ``ReplayFeed`` boundary; the actor holds no learner state beyond its
    local θ copy.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    # tracing config rides the pickled cfg into the spawned child; spans
    # from this process export as their own shard (trace-<pid>.json)
    tracing.configure_from(cfg.trace)
    # The env var alone is NOT enough on hosts whose sitecustomize
    # pre-imports jax with an accelerator platform pinned: jax latches
    # the env into its config default AT IMPORT, so a spawned actor that
    # only sets the env still initializes the accelerator client on its
    # first op (measured: recurrent actors hung on the remote compile
    # service, never delivering a transition). Overriding the config
    # works until the backend is first used — which is exactly now.
    import jax

    jax.config.update("jax_platforms", "cpu")
    # late imports: after the platform pin, inside the child process
    from distributed_deep_q_tpu.actors.game import (
        FrameStacker, NStepAccumulator, StepLatencyEnv, make_env)
    from distributed_deep_q_tpu.models.qnet import QNet
    from distributed_deep_q_tpu.rpc.resilience import (
        ResilientReplayFeedClient, RetryPolicy)

    from distributed_deep_q_tpu.config import env_for_actor
    if int(cfg.actors.vector_envs) > 1 and cfg.net.kind != "r2d2":
        # Sebulba mode (ISSUE 11): this process drives vector_envs
        # stacked env copies behind one batched step — same identities,
        # same wire path, V streams
        _vector_actor_loop(cfg, host, port, actor_id, stop_event,
                           max_env_steps)
        return
    # global identity: actor_id is the LOCAL id (= per-host replay stream);
    # seeding and the ε ladder use the fleet-global id so multi-host slices
    # decorrelate instead of repeating each other (config 5 full shape).
    # Under assignment="hash" the supervisor hands each host an explicit
    # gid slice (actors/assignment.py) instead of a contiguous offset
    gid = (cfg.actors.actor_gids[actor_id] if cfg.actors.actor_gids
           else actor_id + cfg.actors.actor_id_offset)
    fleet = cfg.actors.fleet_size or cfg.actors.num_actors
    env = StepLatencyEnv(make_env(env_for_actor(cfg.env, gid),
                                  seed=cfg.train.seed + 1000 * (gid + 1)))
    cfg.net.num_actions = env.num_actions
    qnet = QNet(cfg.net, seed=cfg.train.seed,
                obs_dim=int(np.prod(env.obs_shape)))
    # resilient stub: transient server outages (restart, network blip) are
    # absorbed by retry/backoff with idempotent flush_seq stamping, so a
    # learner restart means reconnect-and-resend, not an actor death —
    # the restart storm the bare stub caused (every blip → fleet respawn)
    client = ResilientReplayFeedClient.connect(
        host, port, actor_id=actor_id,
        policy=RetryPolicy(base_delay=cfg.actors.rpc_retry_base,
                           max_delay=cfg.actors.rpc_retry_max,
                           deadline=cfg.actors.rpc_retry_deadline),
        timeout=cfg.actors.rpc_call_timeout,
        should_abort=stop_event.is_set,
        seed=cfg.train.seed + 31337 * (gid + 1))
    # announce a fresh writer on this stream id: the server seals the
    # previous writer's slot so no sampled window straddles a restart seam
    client.call("reset_stream")
    rng = np.random.default_rng(cfg.train.seed + 7777 * (gid + 1))
    eps = actor_epsilon(gid, fleet, cfg.actors.eps_base,
                        cfg.actors.eps_alpha)

    if cfg.net.kind == "r2d2":
        _recurrent_actor_loop(cfg, env, qnet, client, rng, eps, stop_event,
                              max_env_steps)
        return

    pixel = env.obs_dtype == np.uint8
    stacker = FrameStacker(env.obs_shape, cfg.env.stack) if pixel else None
    nstep = (None if pixel else
             NStepAccumulator(cfg.replay.n_step, cfg.train.gamma))

    # outgoing chunk buffers
    chunk: dict[str, list] = {k: [] for k in
                              ("frame", "action", "reward", "done", "boundary",
                               "obs", "next_obs", "discount")}
    ep_returns: list[float] = []
    # per-row birth stamps (lineage plane) — only populated while tracing
    # is enabled, so the disabled path never touches the list
    births: list[float] = []
    episodes = 0
    steps = 0

    def flush() -> None:
        nonlocal episodes
        if not chunk["action"]:
            return
        if pixel:
            payload = {
                "frame": np.stack(chunk["frame"]).astype(np.uint8),
                "action": np.asarray(chunk["action"], np.int32),
                "reward": np.asarray(chunk["reward"], np.float32),
                "done": np.asarray(chunk["done"], bool),
                "boundary": np.asarray(chunk["boundary"], bool),
            }
        else:
            payload = {
                "obs": np.stack(chunk["obs"]).astype(np.float32),
                "action": np.asarray(chunk["action"], np.int32),
                "reward": np.asarray(chunk["reward"], np.float32),
                "next_obs": np.stack(chunk["next_obs"]).astype(np.float32),
                "discount": np.asarray(chunk["discount"], np.float32),
            }
        payload["episodes"] = episodes
        payload["ep_returns"] = np.asarray(ep_returns, np.float32)
        payload.update(comms.drain_telemetry())
        step_ms = env.drain_step_ms()
        if step_ms:
            payload["tm_env_step_ms"] = np.asarray(step_ms, np.float32)
        if births:
            if tracing.lineage_sample():
                # birth stamps ship pre-corrected to the SERVER clock so
                # the server's age math needs no per-actor skew state
                payload[tracing.KEY_BIRTH] = tracing.to_server_clock(
                    np.asarray(births, np.float64))
            births.clear()
        resp = client.add_transitions(**payload)
        comms.note_published(resp.get("params_version"))
        for v in chunk.values():
            v.clear()
        ep_returns.clear()
        episodes = 0

    frame = env.reset()
    obs = stacker.reset(frame) if pixel else frame
    ep_ret = 0.0
    # θ refresh over the RPC boundary (SURVEY §5.8) + background liveness
    # beat, independent of env stepping
    comms = _ActorComms(cfg, client, qnet, rng)
    # credit throttling / SHED waits advance the liveness watermark: a
    # backpressured actor is waiting on purpose, not wedged
    client.on_backpressure = comms.touch
    remote = None
    if cfg.inference.enabled:
        # remote_inference mode (ISSUE 9): exploit actions come from the
        # batched inference plane; this actor never pulls θ again
        remote = _RemoteInference(cfg, stop_event, actor_id, gid,
                                  touch=comms.touch)
    try:
        while not stop_event.is_set():
            if max_env_steps and steps >= max_env_steps:
                break
            if remote is None:
                comms.maybe_pull(steps)
            else:
                comms.touch()  # loop progress for the heartbeat gate

            # ε-greedy stays local either way: the SAME rng draws in the
            # SAME order, so the exploration stream is bitwise identical
            # between local and remote inference
            if rng.random() < eps:
                a = int(rng.integers(env.num_actions))
            elif remote is not None:
                with tracing.span_sampled("remote_infer"):
                    a = remote.action(obs)
            else:
                a = qnet.argmax_action(np.asarray(obs))
            with tracing.span_sampled("env_step"):
                next_frame, r, done, over = env.step(a)
            ep_ret += r
            steps += 1

            if pixel:
                chunk["frame"].append(frame)
                chunk["action"].append(a)
                chunk["reward"].append(r)
                chunk["done"].append(done)
                chunk["boundary"].append(over)
                if tracing.ENABLED:
                    births.append(tracing.now())
                frame = next_frame
                obs = stacker.push(frame)
            else:
                emitted = nstep.push(obs, a, r, next_frame, done)
                if over and not done:
                    emitted += nstep.flush_truncated(next_frame)
                for (o, ac, rw, no, disc) in emitted:
                    chunk["obs"].append(o)
                    chunk["action"].append(ac)
                    chunk["reward"].append(rw)
                    chunk["next_obs"].append(no)
                    chunk["discount"].append(disc)
                    if tracing.ENABLED:
                        births.append(tracing.now())
                obs = next_frame

            if over:
                ep_returns.append(ep_ret)
                episodes += 1
                ep_ret = 0.0
                frame = env.reset()
                if pixel:
                    obs = stacker.reset(frame)
                else:
                    obs = frame
                    nstep.reset()

            if len(chunk["action"]) >= cfg.actors.send_batch:
                flush()
        flush()
    except (ConnectionError, OSError):
        pass  # learner gone; supervisor owns our lifecycle
    finally:
        comms.close()
        if remote is not None:
            remote.close()
        client.close()
        if tracing.ENABLED:
            tracing.export()


def _liveness_id(cfg: Config, actor_id: int) -> int:
    """The ``last_seen`` key a vector actor's heartbeat lane uses.

    In vector mode the replay STREAM ids are ``process*V + row``, so
    process p's row-r stream would alias process ``p*V + r``'s liveness
    key — a live process 0 could mask a dead process 1 forever. The
    heartbeat client therefore signs in on a lane BEYOND the stream
    range (``num_actors*V + process``); streams keep their own ids."""
    v = max(int(cfg.actors.vector_envs), 1)
    return cfg.actors.num_actors * v + actor_id if v > 1 else actor_id


def _vector_actor_loop(cfg: Config, host: str, port: int, actor_id: int,
                       stop_event, max_env_steps: int = 0) -> None:
    """Vectorized actor process body (ISSUE 11, Sebulba half of the
    Podracer split): V stacked envs, one batched policy call per wall
    tick, V per-row replay streams down the existing columnar wire path.

    Identity discipline is what makes this a MODE and not a fork: row j
    of process i plays fleet-global id ``base*V + j`` (``base`` = this
    process's gid), with exactly the per-env fleet's seeds — env seed
    ``seed + 1000*(gid+1)``, ε rng ``seed + 7777*(gid+1)``, ε ladder
    slot ``gid`` of ``num_actors*V`` — and ships on replay stream
    ``actor_id*V + j``. Same seeds → same actions → same transitions,
    bitwise (tests/test_vector_env.py pins it on both torsos).
    """
    from distributed_deep_q_tpu.actors.game import make_envs
    from distributed_deep_q_tpu.actors.vector import (
        VectorActing, VectorEnv, VectorStepLatencyEnv)
    from distributed_deep_q_tpu.config import env_for_actor
    from distributed_deep_q_tpu.models.qnet import QNet
    from distributed_deep_q_tpu.rpc.resilience import (
        ResilientReplayFeedClient, RetryPolicy)

    v = int(cfg.actors.vector_envs)
    base = (cfg.actors.actor_gids[actor_id] if cfg.actors.actor_gids
            else actor_id + cfg.actors.actor_id_offset)
    gids = [base * v + j for j in range(v)]
    fleet = cfg.actors.fleet_size or cfg.actors.num_actors * v
    venv = VectorStepLatencyEnv(VectorEnv(make_envs(
        [env_for_actor(cfg.env, g) for g in gids],
        [cfg.train.seed + 1000 * (g + 1) for g in gids])))
    cfg.net.num_actions = venv.num_actions
    # ONE shared θ copy: every per-env actor seeds its QNet with
    # cfg.train.seed, so one net IS all of them
    qnet = QNet(cfg.net, seed=cfg.train.seed,
                obs_dim=int(np.prod(venv.obs_shape)))

    def _policy() -> "RetryPolicy":
        return RetryPolicy(base_delay=cfg.actors.rpc_retry_base,
                           max_delay=cfg.actors.rpc_retry_max,
                           deadline=cfg.actors.rpc_retry_deadline)

    # per-row stream clients: stream id actor_id*V + j keeps the
    # server-side contract intact — flush_seq dedup, slot ownership,
    # and per-stream telemetry all key on it, exactly as V processes
    clients = []
    for j, g in enumerate(gids):
        c = ResilientReplayFeedClient.connect(
            host, port, actor_id=actor_id * v + j, policy=_policy(),
            timeout=cfg.actors.rpc_call_timeout,
            should_abort=stop_event.is_set,
            seed=cfg.train.seed + 31337 * (g + 1))
        c.call("reset_stream")
        clients.append(c)
    # heartbeat/θ lane on its own liveness id (see _liveness_id) with a
    # DEDICATED rng: _ActorComms draws its pull phase at construction,
    # and that draw must not perturb any row's ε stream
    comms_client = ResilientReplayFeedClient.connect(
        host, port, actor_id=_liveness_id(cfg, actor_id), policy=_policy(),
        timeout=cfg.actors.rpc_call_timeout,
        should_abort=stop_event.is_set,
        seed=cfg.train.seed + 31337 * (fleet + actor_id + 1))
    comms = _ActorComms(cfg, comms_client, qnet,
                        np.random.default_rng(
                            cfg.train.seed + 4242 * (actor_id + 1)))
    comms_client.on_backpressure = comms.touch
    for c in clients:
        c.on_backpressure = comms.touch

    rngs = [np.random.default_rng(cfg.train.seed + 7777 * (g + 1))
            for g in gids]
    epsilons = [actor_epsilon(g, fleet, cfg.actors.eps_base,
                              cfg.actors.eps_alpha) for g in gids]
    acting = VectorActing(venv, cfg.env.stack, rngs, epsilons)

    remote = None
    if cfg.inference.enabled:
        remote = _RemoteInference(cfg, stop_event, actor_id * v, base,
                                  touch=comms.touch)

    infer_ms: list[float] = []
    infer_rows: list[float] = []

    def greedy_fn(rows: np.ndarray) -> np.ndarray:
        if remote is not None:
            with tracing.span_sampled("vector_infer"):
                t0 = time.perf_counter()
                out = remote.actions(rows)
            infer_ms.append(1e3 * (time.perf_counter() - t0))
            infer_rows.append(float(len(rows)))
            return out
        return np.argmax(np.asarray(qnet.forward(rows)), axis=-1)

    chunks = [{k: [] for k in ("frame", "action", "reward", "done",
                               "boundary")} for _ in range(v)]
    births: list[list[float]] = [[] for _ in range(v)]
    ep_rets: list[list[float]] = [[] for _ in range(v)]
    episodes = [0] * v
    resets_sent = 0

    def flush(j: int) -> None:
        nonlocal resets_sent
        ch = chunks[j]
        if not ch["action"]:
            return
        payload = {
            "frame": np.stack(ch["frame"]).astype(np.uint8),
            "action": np.asarray(ch["action"], np.int32),
            "reward": np.asarray(ch["reward"], np.float32),
            "done": np.asarray(ch["done"], bool),
            "boundary": np.asarray(ch["boundary"], bool),
            "episodes": episodes[j],
            "ep_returns": np.asarray(ep_rets[j], np.float32),
        }
        # process-level telemetry rides whichever stream flushes next
        # (drain semantics — each sample ships exactly once)
        payload.update(comms.drain_telemetry())
        step_ms = venv.drain_step_ms()
        if step_ms:
            tick_ms = np.asarray(step_ms, np.float32)
            payload["tm_vector_step_ms"] = tick_ms
            # amortized per-env step cost feeds the SAME fleet histogram
            # the per-env actors populate, so the two modes compare on
            # one axis
            payload["tm_env_step_ms"] = tick_ms / v
        if infer_ms:
            payload["tm_vector_infer_ms"] = np.asarray(infer_ms, np.float32)
            infer_ms.clear()
        if infer_rows:
            payload["tm_vector_rows"] = np.asarray(infer_rows, np.float32)
            infer_rows.clear()
        new_resets = acting.auto_resets - resets_sent
        if new_resets:
            payload["tm_vector_resets"] = np.asarray(
                [new_resets], np.float32)
            resets_sent = acting.auto_resets
        if births[j]:
            if tracing.lineage_sample():
                payload[tracing.KEY_BIRTH] = tracing.to_server_clock(
                    np.asarray(births[j], np.float64))
            births[j].clear()
        resp = clients[j].add_transitions(**payload)
        comms.note_published(resp.get("params_version"))
        for q in ch.values():
            q.clear()
        ep_rets[j].clear()
        episodes[j] = 0

    ticks = 0
    steps = 0
    try:
        while not stop_event.is_set():
            if max_env_steps and steps >= max_env_steps:
                break
            if remote is None:
                comms.maybe_pull(ticks)
            else:
                comms.touch()
            with tracing.span_sampled("vector_step"):
                frames, actions, rewards, dones, overs = \
                    acting.tick(greedy_fn)
            now = tracing.now() if tracing.ENABLED else 0.0
            for j in range(v):
                ch = chunks[j]
                ch["frame"].append(frames[j])
                ch["action"].append(int(actions[j]))
                ch["reward"].append(float(rewards[j]))
                ch["done"].append(bool(dones[j]))
                ch["boundary"].append(bool(overs[j]))
                if tracing.ENABLED:
                    births[j].append(now)
                if overs[j]:
                    episodes[j] += 1
            for j, ret in acting.drain_completed():
                ep_rets[j].append(ret)
            ticks += 1
            steps += v
            for j in range(v):
                if len(chunks[j]["action"]) >= cfg.actors.send_batch:
                    flush(j)
        for j in range(v):
            flush(j)
    except (ConnectionError, OSError):
        pass  # learner gone; supervisor owns our lifecycle
    finally:
        comms.close()
        if remote is not None:
            remote.close()
        for c in clients:
            c.close()
        comms_client.close()
        if tracing.ENABLED:
            tracing.export()


def _recurrent_actor_loop(cfg: Config, env, qnet, client, rng, eps: float,
                          stop_event, max_env_steps: int = 0) -> None:
    """R2D2 actor body: thread LSTM state through the episode, assemble
    overlapping sequences with the stored start-of-window carry
    (``SequenceBuilder``), and ship whole sequences over the RPC boundary.

    The carry ALWAYS advances (even on random actions) so the carry stored
    with each sequence matches what the policy network actually saw — the
    stored-state burn-in strategy (SURVEY §5.7) is meaningless otherwise.
    """
    from distributed_deep_q_tpu.actors.game import FrameStacker
    from distributed_deep_q_tpu.replay.sequence import SequenceBuilder

    pixel = env.obs_dtype == np.uint8
    stacker = FrameStacker(env.obs_shape, cfg.env.stack) if pixel else None
    obs_shape = (tuple(env.obs_shape) + (cfg.env.stack,)) if pixel \
        else tuple(env.obs_shape)
    obs_dtype = np.uint8 if pixel else np.float32
    builder = SequenceBuilder(cfg.replay.sequence_length, cfg.replay.burn_in,
                              obs_shape, obs_dtype, cfg.net.lstm_size,
                              cfg.train.gamma)
    # one RPC message per ~send_batch transitions, in whole-sequence units
    period = max(cfg.replay.sequence_length - cfg.replay.burn_in, 1)
    send_seqs = max(1, cfg.actors.send_batch // period)

    seqs: list[dict] = []
    ep_returns: list[float] = []
    births: list[float] = []  # per-env-step birth stamps (tracing only)
    episodes = 0
    env_steps_since = 0
    steps = 0

    def flush() -> None:
        nonlocal episodes, env_steps_since
        if not seqs:
            return
        payload: dict = {k: np.stack([s[k] for s in seqs]) for k in seqs[0]}
        payload["episodes"] = episodes
        payload["ep_returns"] = np.asarray(ep_returns, np.float32)
        payload["env_steps"] = env_steps_since
        payload.update(comms.drain_telemetry())
        step_ms = getattr(env, "drain_step_ms", lambda: [])()
        if step_ms:
            payload["tm_env_step_ms"] = np.asarray(step_ms, np.float32)
        if births:
            if tracing.lineage_sample():
                # rows ≠ ring slots for overlapping sequences, so the
                # server folds these into the flush-level ingest-lag
                # histogram only (no per-slot lineage mapping)
                payload[tracing.KEY_BIRTH] = tracing.to_server_clock(
                    np.asarray(births, np.float64))
            births.clear()
        resp = client.add_transitions(**payload)
        comms.note_published(resp.get("params_version"))
        seqs.clear()
        ep_returns.clear()
        episodes = 0
        env_steps_since = 0

    frame = env.reset()
    obs = stacker.reset(frame) if pixel else frame
    carry = qnet.initial_state(1)
    ep_ret = 0.0
    comms = _ActorComms(cfg, client, qnet, rng)
    client.on_backpressure = comms.touch
    try:
        while not stop_event.is_set():
            if max_env_steps and steps >= max_env_steps:
                break
            comms.maybe_pull(steps)

            carry_before = carry
            q, carry = qnet.forward(np.asarray(obs)[None, None], carry)
            if rng.random() < eps:
                a = int(rng.integers(env.num_actions))
            else:
                a = int(np.argmax(np.asarray(q)[0, 0]))
            with tracing.span_sampled("env_step"):
                next_frame, r, done, over = env.step(a)
            next_obs = stacker.push(next_frame) if pixel else next_frame
            ep_ret += r
            steps += 1
            env_steps_since += 1
            if tracing.ENABLED:
                births.append(tracing.now())
            seqs.extend(builder.on_step(
                obs, a, r, done,
                (np.asarray(carry_before[0])[0],
                 np.asarray(carry_before[1])[0]),
                next_obs))
            obs = next_obs

            if over:
                if not done:
                    # time-limit truncation: ship the window tail with its
                    # bootstrap intact
                    seqs.extend(builder.flush_truncated(next_obs))
                ep_returns.append(ep_ret)
                episodes += 1
                ep_ret = 0.0
                builder.reset()
                frame = env.reset()
                obs = stacker.reset(frame) if pixel else frame
                carry = qnet.initial_state(1)

            if len(seqs) >= send_seqs:
                flush()
        flush()
    except (ConnectionError, OSError):
        pass  # learner gone; supervisor owns our lifecycle
    finally:
        comms.close()
        client.close()
        if tracing.ENABLED:
            tracing.export()


# ---------------------------------------------------------------------------
# Supervisor (failure detection, SURVEY §5.3)
# ---------------------------------------------------------------------------


class ActorSupervisor:
    """Spawns the actor fleet and restarts dead or silent actors.

    The fleet is ELASTIC (ISSUE 20): the autoscale executor grows and
    retires actors at runtime through ``grow``/``retire``, so the
    process map moves under ``_procs_lock`` — the watch loop re-checks
    membership under it before acting, which is what keeps a concurrent
    retirement from being "helpfully" respawned. Executor-initiated
    terminations are counted in ``executor_terminations``, SEPARATE
    from ``kill_escalations`` (crash-kill SIGKILL escalations), so a
    scale-down never reads as a crash in ``telemetry_report``.
    """

    def __init__(self, cfg: Config, host: str, port: int,
                 heartbeat_timeout: float = 60.0,
                 spawn_grace: float = 120.0, target=None):
        self.cfg = cfg
        self.host, self.port = host, port
        self.heartbeat_timeout = heartbeat_timeout
        # first-contact deadline for a fresh (re)spawn: generous — a child
        # needs tens of seconds to import jax on a loaded 1-core host —
        # but finite, so an actor that hangs BEFORE its first heartbeat
        # (wedged env ctor, dead DNS) is still detected and replaced
        self.spawn_grace = max(spawn_grace, heartbeat_timeout)
        # the child entry point: actor_main unless a harness substitutes
        # a lightweight worker (same (cfg, host, port, i, stop) shape)
        self._target = target or actor_main
        self._ctx = mp.get_context("spawn")
        # parent-side master switch (watch-loop pacing). Children get a
        # PRIVATE per-incarnation event instead: a child terminated
        # while parked in mp.Event.wait() dies still registered as a
        # sleeper on the event's shared Condition, and the next set()
        # on that event deadlocks (see _RemoteInference._local_stop).
        # With the executor retiring HEALTHY actors — which are usually
        # parked in wait() — a shared event would wedge stop() almost
        # every scale-down; a private one is orphaned harmlessly.
        self.stop_event = self._ctx.Event()
        self._procs_lock = threading.RLock()
        self._child_stops: dict[int, Any] = {}
        self.procs: dict[int, Any] = {}
        self.spawned_at: dict[int, float] = {}
        self.retired: set[int] = set()
        self.restarts = 0
        self.kill_escalations = 0
        self.executor_terminations = 0
        self._watch: threading.Thread | None = None

    def _spawn(self, i: int) -> None:
        ev = self._ctx.Event()
        p = self._ctx.Process(
            target=self._target,
            args=(self.cfg, self.host, self.port, i, ev),
            name=f"actor-{i}", daemon=True)
        p.start()
        with self._procs_lock:
            self.procs[i] = p
            self._child_stops[i] = ev
            self.spawned_at[i] = time.monotonic()

    def start(self) -> None:
        for i in range(self.cfg.actors.num_actors):
            self._spawn(i)

    # -- elastic surface (the autoscale executor's verbs) --------------------

    def fleet_size(self) -> int:
        with self._procs_lock:
            return len(self.procs)

    def actor_ids(self) -> list[int]:
        with self._procs_lock:
            return sorted(self.procs)

    def grow(self) -> int:
        """Start one more actor: reuse the lowest retired slot (its
        replay stream was evicted, the id is clean) else mint the next
        id. Returns the actor id."""
        with self._procs_lock:
            if self.retired:
                i = min(self.retired)
                self.retired.discard(i)
            else:
                i = max(self.procs) + 1 if self.procs else 0
        self._spawn(i)
        return i

    def retire(self, i: int) -> bool:
        """Executor-initiated scale-down of one actor: remove it from
        the supervised map FIRST (so the watch loop cannot respawn it),
        then terminate. Counted separately from crash-kills."""
        with self._procs_lock:
            p = self.procs.pop(i, None)
            self.spawned_at.pop(i, None)
            ev = self._child_stops.pop(i, None)
            if p is not None:
                self.retired.add(i)
        if p is None:
            return False
        # polite first: signal the child's private stop event and give
        # it a moment to exit its loop — a drained, healthy actor then
        # leaves without ever seeing SIGTERM. _reap is a no-op on an
        # already-exited process, the escalation ladder otherwise.
        if ev is not None:
            ev.set()
            p.join(timeout=2)
        self._reap(p)
        with self._procs_lock:
            self.executor_terminations += 1
        return True

    def reap_actor(self, i: int) -> bool:
        """Rollback path: reap a just-grown actor that missed its grace
        window and release its slot for the next grow."""
        return self.retire(i)

    def _is_silent(self, now: float, last: float, spawned: float) -> bool:
        """Liveness verdict for one actor. Contact since the last
        (re)spawn → plain heartbeat timeout. No contact yet (stale stamps
        from a previous incarnation count as none) → the spawn-grace
        deadline, so an actor that hangs BEFORE its first heartbeat is
        still replaced instead of living forever off a zero stamp."""
        if last > spawned:
            return now - last > self.heartbeat_timeout
        return now - spawned > self.spawn_grace

    def _reap(self, p) -> None:
        """terminate → join → kill escalation. A child that shrugs off
        SIGTERM (wedged in native code, masked handler) would otherwise
        linger as a zombie holding its fds and replay stream; SIGKILL is
        non-negotiable, and each escalation is counted for telemetry."""
        if p.is_alive():
            p.terminate()
        p.join(timeout=5)
        if p.is_alive():
            p.kill()
            p.join(timeout=5)
            with self._procs_lock:
                self.kill_escalations += 1

    def watch(self, last_seen: dict[int, float],
              poll_period: float = 2.0) -> None:
        """Background liveness loop: restart on process death or heartbeat
        silence (``last_seen`` is the ReplayFeed server's contact map)."""
        def loop() -> None:
            while not self.stop_event.is_set():
                now = time.monotonic()
                with self._procs_lock:
                    snap = list(self.procs.items())
                    spawned = dict(self.spawned_at)
                for i, p in snap:
                    dead = not p.is_alive()
                    silent = self._is_silent(
                        now, last_seen.get(_liveness_id(self.cfg, i), 0.0),
                        spawned.get(i, 0.0))
                    if dead or silent:
                        with self._procs_lock:
                            if self.procs.get(i) is not p:
                                continue  # retired/replaced concurrently
                            self.restarts += 1
                        self._reap(p)
                        self._spawn(i)
                time.sleep(poll_period)

        self._watch = threading.Thread(target=loop, name="actor-supervisor",
                                       daemon=True)
        self._watch.start()

    def stop(self, timeout: float = 10.0) -> None:
        self.stop_event.set()
        with self._procs_lock:
            procs = list(self.procs.values())
            events = list(self._child_stops.values())
        # only live, supervised children share these events (a retired
        # or respawned incarnation's event was popped with it), so set()
        # here cannot trip the dead-sleeper deadlock
        for ev in events:
            ev.set()
        for p in procs:
            p.join(timeout=timeout)
            if p.is_alive():
                self._reap(p)


# ---------------------------------------------------------------------------
# Distributed training loop (learner side)
# ---------------------------------------------------------------------------


def _bring_up_rpc_plane(cfg: Config, replay, obs_dim: int = 4):
    """Server + supervised fleet, with the fault-tolerance plumbing:
    chaos spec exported for the spawned actors to inherit, warm boot from
    ``train.server_snapshot_path`` (stable port when snapshotting — a
    restarted learner must come back where the fleet expects it).

    When ``inference.enabled`` the batched inference plane comes up
    alongside the replay feed: its bound address is written back into
    ``cfg.inference`` BEFORE the supervisor is constructed, because the
    fleet learns the address through the cfg pickled into each spawned
    child. Returns ``(server, sup, infer_server-or-None)``."""
    from distributed_deep_q_tpu.rpc import faultinject
    from distributed_deep_q_tpu.rpc.flowcontrol import FlowConfig
    from distributed_deep_q_tpu.rpc.replay_server import ReplayFeedServer

    if cfg.actors.chaos:
        os.environ[faultinject.ENV_VAR] = cfg.actors.chaos
    snap = cfg.train.server_snapshot_path
    flow = FlowConfig(
        flush_credit_floor=cfg.actors.flush_credit_floor,
        staged_high_watermark=cfg.replay.staged_high_watermark,
        shed_policy=cfg.replay.shed_policy,
        rss_high_watermark_mb=cfg.replay.rss_high_watermark_mb)
    server = ReplayFeedServer(replay, host=cfg.actors.host,
                              port=cfg.actors.port if snap else 0,
                              snapshot_path=snap, flow=flow,
                              snapshot_keep=cfg.train.snapshot_keep)
    infer_server = None
    if cfg.inference.enabled and cfg.net.kind != "r2d2":
        from distributed_deep_q_tpu.models.policy import BatchedPolicy
        from distributed_deep_q_tpu.rpc.inference_server import \
            InferenceServer
        policy = BatchedPolicy(cfg.net, seed=cfg.train.seed,
                               obs_dim=obs_dim,
                               buckets=cfg.inference.buckets)
        infer_server = InferenceServer(
            policy, host=cfg.inference.host, port=cfg.inference.port,
            max_batch=cfg.inference.max_batch,
            cutoff_us=cfg.inference.cutoff_us,
            flow=FlowConfig(
                staged_high_watermark=cfg.inference.queue_high_watermark,
                shed_policy=cfg.replay.shed_policy),
            tenants=cfg.inference.tenants,
            shed_shadow_frac=cfg.inference.shed_shadow_frac,
            shed_ab_frac=cfg.inference.shed_ab_frac,
            ladder_burn_s=cfg.inference.ladder_burn_s)
        cfg.inference.host, cfg.inference.port = infer_server.address
    host, port = server.address
    # elastic-fleet registry (ISSUE 17): the learner host seeds the
    # membership plane with itself, so fleet_* verbs answer on this
    # wire from the first actor connection on — joiners and leavers
    # mutate the epoch at runtime, no reboot
    from distributed_deep_q_tpu.actors.membership import MembershipRegistry
    registry = MembershipRegistry()
    registry.join(f"host-{cfg.mesh.process_id}", host, port)
    server.attach_membership(registry)
    sup = ActorSupervisor(cfg, host, port)
    sup.start()
    sup.watch(server.last_seen)
    return server, sup, infer_server


def _publish_weights(server, infer_server, weights) -> None:
    """One θ publish across both planes: the replay feed's cached wire
    frame (local-inference pulls) and the inference server's in-process
    install, tied to the SAME version number so actors on either plane
    agree on what \"current\" means."""
    version = server.publish_params(weights)
    if infer_server is not None:
        infer_server.set_params(weights, version=version)


def _bring_up_health_plane(cfg: Config, server, infer_server=None,
                           solver=None, replay=None, fused: bool = False):
    """Fleet health aggregator + live MFU meter (ISSUE 13).

    Every RPC-plane member's ``health_scrape`` registers with ONE
    ``FleetHealth`` — both servers live in the learner process, so the
    scrape is an in-process call (a remote member would register its
    client stub's ``.health`` instead; same wire dict either way). The
    MFU meter gets a flops-per-step census only on the fused device-PER
    path (the flagship program bench's offline MFU times) and only when
    the health plane is on — the census is one extra AOT compile, which
    a default run must not pay. Returns ``(fleet, meter)``; both are
    inert no-ops while ``health.ENABLED`` is off."""
    fleet = health.FleetHealth()
    fleet.register("replay", server.health_scrape)
    if infer_server is not None:
        fleet.register("inference", infer_server.health_scrape)
    flops = peak = None
    if health.ENABLED:
        from distributed_deep_q_tpu.profiling import (
            fused_train_flops, peak_flops_for)
        peak = peak_flops_for()
        if fused and solver is not None and replay is not None:
            flops = fused_train_flops(solver, replay,
                                      cfg.replay.fused_chain)
    from distributed_deep_q_tpu.profiling import MFUMeter
    return fleet, MFUMeter(flops, peak)


def _bring_up_autoscaler(cfg: Config, sup=None, server=None):
    """Health-driven autoscaler (ISSUE 17) + its executor (ISSUE 20).

    Returns ``(autoscaler, executor)`` — ``(None, None)`` unless BOTH
    the health plane and ``cfg.autoscale`` are enabled (the scaler's
    only input is the fleet verdict, so without scrapes it could only
    ever no-op). The executor additionally needs ``autoscale.execute``
    plus a supervisor to drive; it drains/evicts through the replay
    server and checks spawn-grace heartbeats against its contact map."""
    if not (health.ENABLED and cfg.autoscale.enabled):
        return None, None
    from distributed_deep_q_tpu.actors.autoscaler import Autoscaler
    a = cfg.autoscale
    boot = cfg.actors.fleet_size or cfg.actors.num_actors
    scaler = Autoscaler(
        min_actors=min(a.min_actors, boot),
        max_actors=a.max_actors or boot,
        min_inference=a.min_inference, max_inference=a.max_inference,
        step=a.step, cooldown_s=a.cooldown_s,
        recover_ticks=a.recover_ticks)
    executor = None
    if a.execute and sup is not None:
        from distributed_deep_q_tpu.actors.executor import ScaleExecutor
        hb = None
        seq = None
        evict = None
        if server is not None:
            spawned = sup.spawned_at

            def hb(i: int) -> bool:  # noqa: E306 — grace-window check
                return (server.last_seen.get(_liveness_id(cfg, i), 0.0)
                        > spawned.get(i, 0.0))

            seq = server.stream_seq_of
            evict = server.retire_stream
        executor = ScaleExecutor(
            sup, rate_limit_s=a.rate_limit_s, drain_s=a.drain_s,
            spawn_grace_s=a.spawn_grace_s, dry_run=a.dry_run,
            heartbeat_ok=hb, stream_seq=seq, retire_stream=evict)
    return scaler, executor


def _health_tick(fleet, meter, server, gstep: int,
                 scrape: bool = True, autoscaler=None,
                 executor=None) -> dict:
    """Per-log-tick health/efficiency record: live MFU + ingest
    utilization gauges, fleet self-accounting, and the aggregated
    verdict (a JSON-able dict — ``Metrics.log`` passes non-numerics
    through to the run JSONL untouched). Empty while disabled.

    With an autoscaler attached, each FRESH scrape is folded through it
    (stale ``last()`` verdicts would double-count into the recovery
    streak) and any decisions ride the same record under
    ``autoscale/decision`` — rule + burn numbers, lineage-traceable.
    With an EXECUTOR attached (ISSUE 20), the tick's decisions are
    applied synchronously on this thread and every action taken lands
    under ``autoscale/applied`` naming the decision's rule — applied
    vs target is what ``telemetry_report --strict`` audits."""
    if not health.ENABLED:
        return {}
    fc = server.flow_counters()
    out = meter.update(gstep, ingest_rate=fc["ingest_rate"],
                       consume_rate=fc["consume_rate"])
    v = fleet.scrape() if scrape else fleet.last()
    out.update(fleet.gauges())
    if server.membership is not None:
        out.update(server.membership.gauges())
    if autoscaler is not None and scrape:
        decisions = autoscaler.observe(v)
        out.update(autoscaler.gauges())
        if decisions:
            out["autoscale/decision"] = [d.to_jsonable()
                                         for d in decisions]
        if executor is not None:
            applied = executor.apply(decisions)
            out.update(executor.gauges())
            if applied:
                out["autoscale/applied"] = applied
    out["health/verdict"] = v.to_jsonable()
    return out


def _tear_down_rpc_plane(cfg: Config, server, sup, infer_server=None) -> None:
    sup.stop()
    if infer_server is not None:
        infer_server.close()
    snap = cfg.train.server_snapshot_path
    if snap:
        server.shutdown(snap)  # quiesce + snapshot for the next warm boot
    else:
        server.close()


def train_distributed(cfg: Config, metrics: Metrics | None = None,
                      log_every: int = 500) -> dict:
    """Actor fleet over RPC → replay → mesh learner; returns summary.

    The learner samples/train-steps continuously once the buffer is ready;
    actors stream transitions and pull θ through the ``ReplayFeed`` service.
    Total work: ``cfg.train.total_steps`` grad steps (the distributed
    topology's unit of progress is learner steps, matching the north-star
    grad-steps/sec metric).
    """
    import dataclasses

    from distributed_deep_q_tpu.actors.game import make_env
    from distributed_deep_q_tpu.replay.device_ring import DeviceFrameReplay

    if cfg.replay.persist_path:
        raise ValueError(
            "replay.persist_path covers the single-process transition-"
            "replay paths; the distributed topology warm-refills from its "
            "actor fleet on restart (the reference behavior) — unset it "
            "for --distributed runs")
    if cfg.net.kind == "r2d2":
        return _train_distributed_recurrent(cfg, metrics, log_every)
    from distributed_deep_q_tpu.replay.multistream import MultiStreamFrameReplay
    from distributed_deep_q_tpu.replay.prioritized import maybe_prioritize
    from distributed_deep_q_tpu.replay.replay_memory import ReplayMemory
    from distributed_deep_q_tpu.solver import Solver

    metrics = metrics or Metrics()
    tracing.configure_from(cfg.trace)  # learner-process tracer state
    health.configure_from(cfg.health)  # learner-process health plane
    probe = _probe_envs(cfg)
    cfg.net.num_actions = probe.num_actions
    obs_shape = probe.obs_shape
    pixel = probe.obs_dtype == np.uint8
    if int(cfg.actors.vector_envs) > 1 and not pixel:
        # fail HERE, not in the actor subprocess: VectorActing rejects
        # non-uint8 frames at construction, and a dead actor fleet
        # leaves the learner waiting on learn_start forever
        raise ValueError(
            "actors.vector_envs > 1 is the pixel acting path (uint8 "
            f"frames); env {cfg.env.kind}/{cfg.env.id} observes "
            f"{np.dtype(probe.obs_dtype).name} — use a pixel env or "
            "vector_envs=1")
    del probe

    # β anneal is denominated in sample() calls; this topology samples once
    # per grad step (presets precompute it for the single-process cadence of
    # one sample per train_every env steps)
    replay_cfg = dataclasses.replace(
        cfg.replay, priority_beta_steps=cfg.train.total_steps)

    solver = Solver(cfg, obs_dim=int(np.prod(obs_shape)))
    from distributed_deep_q_tpu.parallel.multihost import (
        all_processes_ready, local_rows)
    cfg, local_batch, metrics, pc, pid = _split_fleet_across_processes(
        cfg, pixel, metrics, "mesh-sharded HBM ring", fused_ok=True)
    from distributed_deep_q_tpu.replay.device_per import DevicePERFrameReplay
    if pixel and cfg.replay.device_resident:
        # fused device PER (prioritized + device_per): the learner step
        # samples/updates in HBM, so the lock below covers flush + dispatch
        cls = (DevicePERFrameReplay
               if cfg.replay.prioritized and cfg.replay.device_per
               else DeviceFrameReplay)
        # vector mode: every stacked env row is its own replay stream
        # (slot ownership + flush_seq dedup key on it), so the ring is
        # built for num_actors * V writers
        replay = cls(
            replay_cfg, solver.mesh, obs_shape, cfg.env.stack,
            cfg.train.gamma, seed=cfg.train.seed,
            write_chunk=cfg.replay.write_chunk,
            num_streams=cfg.actors.num_actors
            * max(int(cfg.actors.vector_envs), 1))
    elif pixel:
        if cfg.replay.prioritized:
            raise ValueError(
                "prioritized replay in the distributed pixel topology "
                "requires replay.device_resident=True (the host "
                "MultiStreamFrameReplay fallback is uniform-only)")
        replay = MultiStreamFrameReplay(
            cfg.replay.capacity, obs_shape, cfg.env.stack, cfg.replay.n_step,
            cfg.train.gamma,
            num_streams=cfg.actors.num_actors
            * max(int(cfg.actors.vector_envs), 1),
            seed=cfg.train.seed)
    else:
        replay = maybe_prioritize(
            ReplayMemory(cfg.replay.capacity, obs_shape, np.float32,
                         seed=cfg.train.seed),
            replay_cfg, seed=cfg.train.seed)

    server, sup, infer_server = _bring_up_rpc_plane(
        cfg, replay, obs_dim=int(np.prod(obs_shape)))
    _publish_weights(server, infer_server, solver.get_weights())

    fused_per = isinstance(replay, DevicePERFrameReplay)
    fleet_health, mfu_meter = _bring_up_health_plane(
        cfg, server, infer_server, solver=solver, replay=replay,
        fused=fused_per)
    autoscaler, scale_executor = _bring_up_autoscaler(cfg, sup, server)
    writeback = None
    if replay.prioritized and not fused_per:
        from distributed_deep_q_tpu.replay.prioritized import make_writeback
        # multi-host: each process writes back only its own rows of the
        # batch-sharded |TD|, into its own shard (local_rows)
        writeback = make_writeback(replay, cfg.replay,
                                   lock=server.replay_lock,
                                   to_host=local_rows if pc > 1 else None)
    summary: dict = {}
    from distributed_deep_q_tpu.profiling import (
        StepTimer, TraceWindow, start_profiler_server)
    timer = StepTimer()
    trace = TraceWindow(cfg.train.profile_dir, cfg.train.profile_start_step,
                        cfg.train.profile_num_steps)
    if cfg.train.profile_port:
        start_profiler_server(cfg.train.profile_port)
    from distributed_deep_q_tpu.utils.checkpoint import maybe_checkpointer
    ckpt = maybe_checkpointer(cfg.train)
    if ckpt and cfg.train.resume and ckpt.latest_step() is not None:
        solver.state, _ = ckpt.restore(solver.state)
        _publish_weights(server, infer_server, solver.get_weights())
    stager = None
    try:
        # wait for warm-up fill (actors are streaming meanwhile). Multi-
        # host: the gate opens only when EVERY host's shard is warm — the
        # sharded train step is a collective, no process may enter early.
        # all_processes_ready is itself a collective, so the polling
        # processes proceed in lockstep.
        if pc == 1:
            while not replay.ready(cfg.replay.learn_start):
                time.sleep(0.05)
        else:
            while not all_processes_ready(
                    replay.ready(cfg.replay.learn_start)):
                time.sleep(0.05)
        if not (isinstance(replay, DeviceFrameReplay) or fused_per) \
                and pc == 1:
            # host-batch path: double-buffered sample → device_put pipeline
            # (SURVEY §7.3 item 1); shares the server's replay lock so the
            # background sampler serializes with RPC writers and with PER
            # priority write-back below. Multi-host skips the stager: the
            # global batch assembles from process-local numpy rows inside
            # train_step, so the sample stays synchronous under the lock.
            from distributed_deep_q_tpu.replay.staging import DeviceStager
            stager = DeviceStager(
                lambda: replay.sample(local_batch),
                sharding=solver.learner._batch_sharding, depth=2,
                lock=server.replay_lock)
        from distributed_deep_q_tpu.solver import FusedStepStream
        fused_stream = (FusedStepStream(solver, replay,
                                        cfg.replay.fused_chain,
                                        dispatch_lock=server.replay_lock,
                                        timer=timer)
                        if fused_per else None)
        # learning-dynamics plane (ISSUE 16): the fused chunks return
        # one on-device metrics plane per dispatch; fold them into
        # learn/* gauges + the TD histogram at log cadence and register
        # the learner itself as a fleet-health member so divergence
        # trends (loss_divergence & co) land in the fleet verdict
        learn_acc = learn_monitor = None
        if cfg.train.learn_metrics and fused_per:
            from distributed_deep_q_tpu import learning
            learn_acc = learning.LearnAccumulator()
            learn_monitor = health.HealthMonitor(
                rules=health.default_learn_rules(),
                trends=health.default_learn_trends(), name="learner")
            fleet_health.register(
                "learner", learning.learn_scrape_fn(learn_acc,
                                                    learn_monitor))
        for gstep in range(1, cfg.train.total_steps + 1):
            if fused_per:
                # the fused chunk flushes staged actor rows + dispatches
                # up to fused_chain scanned grad steps in one go; the lock
                # serializes against RPC writers so the donated device
                # state can't be swapped mid-dispatch (and is released
                # while the chunk executes on device — writers get the
                # whole window)
                m = fused_stream.next(cfg.train.total_steps - gstep + 1)
            elif isinstance(replay, DeviceFrameReplay):
                # sample AND dispatch under the lock: a concurrent actor
                # flush donates the current ring buffer, so the step must be
                # enqueued before the ring handle can be invalidated
                # (dispatch is µs; device execution stays async)
                with server.replay_lock:
                    with timer.phase("sample"):
                        batch = replay.sample(local_batch)
                    sampled_at = batch.pop("_sampled_at")
                    with timer.phase("dispatch"):
                        m = solver.train_step_from_ring(
                            replay.ring, batch, replay.frame_shape)
            else:
                if stager is not None:
                    with timer.phase("sample"):  # wait on the pipeline
                        batch = stager.get()
                else:  # multi-host: synchronous local-shard sample
                    with server.replay_lock:
                        with timer.phase("sample"):
                            batch = replay.sample(local_batch)
                sampled_at = batch.pop("_sampled_at", replay.steps_added)
                if tracing.ENABLED and isinstance(batch.get("index"),
                                                  np.ndarray):
                    # lineage lookup at CONSUMPTION: env-step birth →
                    # this gradient step = time_to_learn (host-indexed
                    # tiers only; device tiers keep ingest-lag coverage)
                    ages = server.lineage_ages(batch["index"])
                    if ages.size:
                        metrics.observe_many("learner/time_to_learn_ms",
                                             ages * 1e3)
                with timer.phase("dispatch"):
                    m = solver.train_step(batch)
            metrics.count("grad_steps")
            # feed the flow controller's consumption EWMA: credits granted
            # to actors track what the learner actually drains per step
            server.note_consumed(local_batch)
            timer.step_done()
            trace.on_step(gstep)

            if replay.prioritized and not fused_per:
                # pipelined write-back: the |TD| fetch never blocks the
                # step, and the update itself takes the replay lock
                writeback.push(m["index"], m["td_abs"], sampled_at)

            if gstep % cfg.actors.param_sync_period == 0:
                t0 = time.perf_counter()
                _publish_weights(server, infer_server, solver.get_weights())
                metrics.observe("learner/publish_params_ms",
                                1e3 * (time.perf_counter() - t0))

            if ckpt and gstep % cfg.train.checkpoint_every == 0:
                ckpt.save(solver.state,
                          extra={"env_steps": server.counters()["env_steps"]})
                if cfg.train.server_snapshot_path:
                    # capture-only under the lock; serialize + fsync in a
                    # background thread (a still-running previous dump
                    # just skips this tick — counted, never stacked)
                    server.snapshot_async(cfg.train.server_snapshot_path)

            if gstep % log_every == 0:
                timer.measure_device(m["loss"])
                counts = server.counters()
                summary = {
                    "loss": float(m["loss"]),
                    "q_mean": float(m["q_mean"]),
                    "return_avg100": server.mean_recent_return(),
                    "env_steps": counts["env_steps"],
                    "replay_size": counts["replay_size"],
                    "grad_steps_per_s": metrics.rate("grad_steps"),
                    "actor_restarts": sup.restarts,
                    "actor_kill_escalations": sup.kill_escalations,
                    "actor_scale_terminations": sup.executor_terminations,
                }
                # one record carries the whole telemetry spine: per-phase
                # times, per-RPC-method latency/size percentiles, queue
                # gauges, and the fleet counters actors flushed back
                infer_tm = (infer_server.telemetry_summary()
                            if infer_server is not None else {})
                if learn_acc is not None:
                    # fold this window's planes (D2H happens HERE, at
                    # log cadence) and surface learn/* + the TD-error
                    # histogram summary through the metrics spine
                    for plane in fused_stream.drain_planes():
                        learn_acc.ingest(plane)
                    for lk, lv in learn_acc.gauges().items():
                        metrics.gauge(lk, lv)
                    for lk, lv in learn_acc.hist_snapshot().summary(
                            prefix="learn/td_error").items():
                        metrics.gauge(lk, lv)
                # health plane: live MFU/ingest-utilization gauges + the
                # aggregated fleet verdict (scraped every
                # health.scrape_every log ticks; {} while disabled)
                hk = _health_tick(
                    fleet_health, mfu_meter, server, gstep,
                    scrape=(gstep // log_every)
                    % max(cfg.health.scrape_every, 1) == 0,
                    autoscaler=autoscaler, executor=scale_executor)
                metrics.log(gstep, **summary, **timer.summary(),
                            **server.telemetry_summary(), **infer_tm,
                            **metrics.telemetry(), **hk)
    finally:
        trace.close()
        if stager is not None:
            stager.close()
        _tear_down_rpc_plane(cfg, server, sup, infer_server)
        if tracing.ENABLED:
            tracing.export()  # learner-process shard (actors wrote theirs)

    summary["final_return_avg100"] = server.mean_recent_return()
    if writeback:
        writeback.drain()
    from distributed_deep_q_tpu.train import log_final_eval
    log_final_eval(solver, cfg, metrics, summary)
    summary["env_steps"] = server.counters()["env_steps"]
    summary["actor_restarts"] = sup.restarts
    summary["actor_kill_escalations"] = sup.kill_escalations
    summary["actor_scale_terminations"] = sup.executor_terminations
    rpc = server.telemetry.robustness_counters()
    summary["rpc_dispatch_errors"] = rpc["dispatch_errors"]
    summary["rpc_duplicate_flushes"] = rpc["duplicate_flushes"]
    summary["rpc_shed_flushes"] = rpc["shed_flushes"]
    summary["rpc_checksum_errors"] = rpc["checksum_errors"]
    summary["snapshot_quarantined"] = rpc["snapshot_quarantined"]
    summary["flow_degraded_trips"] = server.flow_counters()["degraded_trips"]
    if infer_server is not None:
        itm = infer_server.telemetry_summary()
        summary["inference_requests"] = int(itm["inference/requests"])
        summary["inference_sheds"] = int(itm["inference/sheds"])
        summary["inference_compiled_buckets"] = int(
            itm["inference/compiled_buckets"])
        # the mode's whole point, as a ledger entry: actors pulled
        # actions, not parameters (heartbeats aside, get_params should
        # never fire once the plane is up)
        with server.telemetry._lock:
            summary["inference_param_pulls"] = int(
                server.telemetry.method_calls.get("get_params", 0))
    summary["solver"] = solver
    summary["replay"] = replay
    return summary


def _train_distributed_recurrent(cfg: Config, metrics: Metrics | None = None,
                                 log_every: int = 500) -> dict:
    """Distributed R2D2 (config 5): recurrent actors over RPC → sequence
    replay → mesh sequence learner.

    Actors run the full recurrent policy (LSTM state threaded through the
    episode) and ship whole sequences with their stored start carry; the
    learner samples sequence batches under the server's replay lock — the
    ``SequenceReplay`` store is host-side and ``sample`` copies rows, so the
    lock covers only the sample/priority write-back, never device execution.
    """
    from distributed_deep_q_tpu.actors.game import make_env
    from distributed_deep_q_tpu.parallel.sequence_learner import SequenceSolver
    from distributed_deep_q_tpu.replay.sequence import SequenceReplay
    from distributed_deep_q_tpu.train import evaluate_recurrent
    from distributed_deep_q_tpu.utils.checkpoint import maybe_checkpointer

    metrics = metrics or Metrics()
    tracing.configure_from(cfg.trace)  # learner-process tracer state
    health.configure_from(cfg.health)  # learner-process health plane
    probe = _probe_envs(cfg)
    cfg.net.num_actions = probe.num_actions
    pixel = probe.obs_dtype == np.uint8
    obs_shape = (tuple(probe.obs_shape) + (cfg.env.stack,)) if pixel \
        else tuple(probe.obs_shape)
    obs_dtype = np.uint8 if pixel else np.float32
    obs_dim = int(np.prod(probe.obs_shape))
    del probe

    solver = SequenceSolver(cfg, obs_dim=obs_dim)
    from distributed_deep_q_tpu.parallel.multihost import (
        all_processes_ready, local_rows)
    # config 5 full shape, recurrent edition: per-host server + actor
    # slice + sequence-replay shard
    cfg, local_batch, metrics, pc, pid = _split_fleet_across_processes(
        cfg, pixel, metrics, "device sequence ring", fused_ok=True)
    seq_len = cfg.replay.sequence_length
    # transition-denominated config fields scale down to sequence units;
    # β anneal runs per sample() = per grad step in this topology
    seq_capacity = max(cfg.replay.capacity // seq_len, 64)
    # device residency: single-controller for the host-sampled per-step
    # path; multi-controller ONLY through the fused ring (per-host
    # staging + lockstep flush — the _split gate enforces prioritized +
    # device_per for pc > 1)
    device_seq = pixel and cfg.replay.device_resident and (
        pc == 1 or (cfg.replay.prioritized and cfg.replay.device_per))
    if device_seq:
        # R2D2 pixel plane in HBM (replay/device_sequence.py): actors
        # stream stacked sequences over RPC unchanged; the server derives
        # the unstacked frame streams and scatters them into the ring once
        from distributed_deep_q_tpu.replay.device_sequence import (
            DeviceSequenceReplay)
        replay = DeviceSequenceReplay(
            seq_capacity, seq_len, obs_shape, solver.mesh,
            cfg.net.lstm_size, prioritized=cfg.replay.prioritized,
            alpha=cfg.replay.priority_alpha, beta0=cfg.replay.priority_beta0,
            beta_steps=cfg.train.total_steps, eps=cfg.replay.priority_eps,
            seed=cfg.train.seed, use_native=cfg.replay.use_native)
    else:
        replay = SequenceReplay(
            seq_capacity, seq_len, obs_shape,
            obs_dtype, cfg.net.lstm_size, prioritized=cfg.replay.prioritized,
            alpha=cfg.replay.priority_alpha, beta0=cfg.replay.priority_beta0,
            beta_steps=cfg.train.total_steps, eps=cfg.replay.priority_eps,
            seed=cfg.train.seed, use_native=cfg.replay.use_native)
    learn_start_seqs = max(cfg.replay.learn_start // seq_len, 2)

    # no inference plane: recurrent actors carry per-episode LSTM state
    # that cannot be microbatched across actors (BatchedPolicy rejects it)
    server, sup, _ = _bring_up_rpc_plane(cfg, replay)
    server.publish_params(solver.get_weights())

    ckpt = maybe_checkpointer(cfg.train)
    if ckpt and cfg.train.resume and ckpt.latest_step() is not None:
        solver.state, _ = ckpt.restore(solver.state)
        server.publish_params(solver.get_weights())

    # fused chained sequence path (round 5): sampling/meta/pixels/
    # priorities on device, chain grad steps per dispatch — the sequence
    # twin of the transition loop's fused_per branch above.
    # Prioritized-only (the device sampler draws from the priority row)
    fused_seq = (device_seq and cfg.replay.device_per
                 and cfg.replay.prioritized)
    # no fused-flops census on the sequence program (its scan carries
    # recurrent state — the transition-path census doesn't apply), so
    # live MFU is absent here; steps/s + ingest utilization still emit
    fleet_health, mfu_meter = _bring_up_health_plane(cfg, server)
    autoscaler, scale_executor = _bring_up_autoscaler(cfg, sup, server)
    writeback = None
    if replay.prioritized and not fused_seq:
        from distributed_deep_q_tpu.replay.prioritized import make_writeback
        writeback = make_writeback(replay, cfg.replay,
                                   lock=server.replay_lock,
                                   to_host=local_rows if pc > 1 else None)
    summary: dict = {}
    from distributed_deep_q_tpu.profiling import StepTimer
    timer = StepTimer()
    try:
        if pc == 1:
            while not replay.ready(learn_start_seqs):
                time.sleep(0.05)
        else:
            # collective learn gate — see train_distributed
            while not all_processes_ready(replay.ready(learn_start_seqs)):
                time.sleep(0.05)
        fused_stream = None
        if fused_seq:
            from distributed_deep_q_tpu.solver import FusedStepStream
            fused_stream = FusedStepStream(solver, replay,
                                           cfg.replay.fused_chain,
                                           dispatch_lock=server.replay_lock)
        for gstep in range(1, cfg.train.total_steps + 1):
            if fused_seq:
                m = fused_stream.next(cfg.train.total_steps - gstep + 1)
            elif device_seq:
                # sample AND dispatch under the lock: a concurrent RPC
                # flush donates the ring buffer, so the gather program
                # must be enqueued before the handle can be invalidated
                # (same discipline as the DeviceFrameReplay loop above)
                with server.replay_lock:
                    with timer.phase("sample"):
                        batch = replay.sample(local_batch)
                    sampled_at = batch.pop("_sampled_at")
                    with timer.phase("dispatch"):
                        m = solver.train_step_from_ring(replay, batch)
            else:
                with server.replay_lock:
                    with timer.phase("sample"):
                        batch = replay.sample(local_batch)
                    sampled_at = batch.pop("_sampled_at")
                if tracing.ENABLED and isinstance(batch.get("index"),
                                                  np.ndarray):
                    ages = server.lineage_ages(batch["index"])
                    if ages.size:
                        metrics.observe_many("learner/time_to_learn_ms",
                                             ages * 1e3)
                with timer.phase("dispatch"):
                    m = solver.train_step(batch)
            metrics.count("grad_steps")
            # consumption is denominated in env transitions (what actors
            # flush), so a sequence batch counts batch × sequence_length
            server.note_consumed(local_batch * cfg.replay.sequence_length)
            timer.step_done()

            if writeback is not None:
                writeback.push(m["index"], m["td_abs"], sampled_at)

            if gstep % cfg.actors.param_sync_period == 0:
                t0 = time.perf_counter()
                server.publish_params(solver.get_weights())
                metrics.observe("learner/publish_params_ms",
                                1e3 * (time.perf_counter() - t0))
            if ckpt and gstep % cfg.train.checkpoint_every == 0:
                ckpt.save(solver.state,
                          extra={"env_steps": server.counters()["env_steps"]})
                if cfg.train.server_snapshot_path:
                    # non-blocking: capture under the lock, write off-lock
                    server.snapshot_async(cfg.train.server_snapshot_path)
            if gstep % log_every == 0:
                counts = server.counters()
                summary = {
                    "loss": float(m["loss"]),
                    "q_mean": float(m["q_mean"]),
                    "return_avg100": server.mean_recent_return(),
                    "env_steps": counts["env_steps"],
                    "replay_size": counts["replay_size"],
                    "grad_steps_per_s": metrics.rate("grad_steps"),
                    "actor_restarts": sup.restarts,
                    "actor_kill_escalations": sup.kill_escalations,
                    "actor_scale_terminations": sup.executor_terminations,
                }
                hk = _health_tick(
                    fleet_health, mfu_meter, server, gstep,
                    scrape=(gstep // log_every)
                    % max(cfg.health.scrape_every, 1) == 0,
                    autoscaler=autoscaler, executor=scale_executor)
                metrics.log(gstep, **summary, **timer.summary(),
                            **server.telemetry_summary(),
                            **metrics.telemetry(), **hk)
    finally:
        _tear_down_rpc_plane(cfg, server, sup)
        if tracing.ENABLED:
            tracing.export()  # learner-process shard (actors wrote theirs)

    summary["final_return_avg100"] = server.mean_recent_return()
    if writeback:
        writeback.drain()
    from distributed_deep_q_tpu.train import log_final_eval
    log_final_eval(solver, cfg, metrics, summary, recurrent=True)
    summary["env_steps"] = server.counters()["env_steps"]
    summary["actor_restarts"] = sup.restarts
    summary["actor_kill_escalations"] = sup.kill_escalations
    summary["actor_scale_terminations"] = sup.executor_terminations
    rpc = server.telemetry.robustness_counters()
    summary["rpc_dispatch_errors"] = rpc["dispatch_errors"]
    summary["rpc_duplicate_flushes"] = rpc["duplicate_flushes"]
    summary["rpc_shed_flushes"] = rpc["shed_flushes"]
    summary["rpc_checksum_errors"] = rpc["checksum_errors"]
    summary["snapshot_quarantined"] = rpc["snapshot_quarantined"]
    summary["flow_degraded_trips"] = server.flow_counters()["degraded_trips"]
    summary["solver"] = solver
    summary["replay"] = replay
    return summary
