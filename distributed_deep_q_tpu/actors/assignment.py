"""Consistent-hash actor→host assignment (ISSUE 10, ROADMAP item 3).

Each learner host owns a full local data plane: a replay shard fed only
by its slice of the actor fleet (FireCaffe's lesson, PAPERS.md
arXiv:1511.00175 — make the gradient allreduce the *only* cross-host
traffic; In-Network Experience Sampling, arXiv:2110.13506 — sample
where the data lands). The slice comes from a consistent-hash ring so
the mapping is

- **a pure function of (fleet, hosts)** — an actor restarting with the
  same global id lands on the same host, so churn never reshuffles the
  fleet (replay stream identity survives restarts, and the supervisor's
  restart path needs no coordination);
- **minimal-remap on host join/leave** — only ~fleet/hosts actors move
  when the host set changes, everyone else keeps their shard (classic
  ring property; the bounded-load cap below perturbs it only at the
  margin);
- **balanced by construction** — plain consistent hashing can leave a
  host with an empty slice, which here is not a latency blip but a
  DEADLOCK: the cross-host learn gate AND-reduces ``replay.ready()``
  and an unfed shard never fills. Assignment therefore walks the ring
  under a load cap of ``ceil(fleet/hosts)`` (bounded-load consistent
  hashing) and a deterministic rebalance pass lifts any host below
  ``floor(fleet/hosts)``, so every host owns between floor and ceil
  actors.

Hosts are identified by stable TOKENS (``host-<pid>``), not network
addresses: a host changing address keeps its token, so its actor slice
is unchanged and the move is just a reconnect through
``ResilientReplayFeedClient`` — exactly the seam ISSUE 10 names.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Sequence

#: virtual nodes per host on the ring — enough that per-host arc length
#: concentrates (stddev ~ 1/sqrt(replicas)) without making ring
#: construction a cost (the ring is rebuilt per call; assignment runs
#: once at spawn, not on a hot path)
REPLICAS = 64


def stable_hash(token: str) -> int:
    """64-bit hash that is stable across processes and runs.

    ``hash()`` is salted per-process (PYTHONHASHSEED); every host must
    compute the identical ring, so use a keyed-nothing blake2b digest.
    """
    return int.from_bytes(
        hashlib.blake2b(token.encode(), digest_size=8).digest(), "big")


def host_tokens(num_hosts: int) -> tuple[str, ...]:
    """Canonical host tokens for a multi-controller learner: one per
    JAX process index. Tokens, not addresses — see module docstring."""
    return tuple(f"host-{i}" for i in range(num_hosts))


def _ring(hosts: Sequence[str],
          replicas: int) -> tuple[list[int], list[str]]:
    pts = sorted(
        (stable_hash(f"{h}#{r}"), h)
        for h in hosts for r in range(replicas))
    return [p for p, _ in pts], [h for _, h in pts]


def owner_host(gid: int, hosts: Sequence[str],
               replicas: int = REPLICAS) -> str:
    """Unbounded ring lookup: the host whose virtual node first follows
    the actor's hash point clockwise. This is the raw ring preference
    ``assign_fleet`` starts from before load bounding."""
    points, owners = _ring(hosts, replicas)
    i = bisect.bisect_right(points, stable_hash(f"actor-{gid}"))
    return owners[i % len(owners)]


def assign_fleet(fleet_size: int, hosts: Sequence[str],
                 replicas: int = REPLICAS) -> dict[str, list[int]]:
    """host token → sorted actor gids, covering ``range(fleet_size)``.

    Bounded-load walk: each gid starts at its ring point and takes the
    first host under the ``ceil(fleet/hosts)`` cap. A deterministic
    rebalance pass then moves actors from the most- to the least-loaded
    host until every host holds at least ``floor(fleet/hosts)`` — an
    empty shard would deadlock the cross-host learn gate (module
    docstring). Pure function of its arguments.
    """
    hosts = list(hosts)
    if not hosts:
        raise ValueError("assign_fleet needs at least one host")
    if len(set(hosts)) != len(hosts):
        raise ValueError(f"duplicate host tokens: {hosts}")
    points, owners = _ring(hosts, replicas)
    n = len(points)
    cap = -(-fleet_size // len(hosts))
    load = {h: 0 for h in hosts}
    out: dict[str, list[int]] = {h: [] for h in hosts}
    for gid in range(fleet_size):
        i = bisect.bisect_right(points, stable_hash(f"actor-{gid}")) % n
        h = next(owners[(i + s) % n] for s in range(n)
                 if load[owners[(i + s) % n]] < cap)
        load[h] += 1
        out[h].append(gid)

    floor = fleet_size // len(hosts)
    while True:
        short = [h for h in hosts if load[h] < floor]
        if not short:
            break
        # deterministic donor/recipient: extreme load, host order breaks
        # ties — every process computes the identical move sequence
        h_to = min(short, key=lambda h: (load[h], hosts.index(h)))
        h_from = max(hosts, key=lambda h: (load[h], -hosts.index(h)))
        out[h_to].append(out[h_from].pop())
        load[h_from] -= 1
        load[h_to] += 1
    return {h: sorted(v) for h, v in out.items()}


def local_slice(fleet_size: int, num_hosts: int,
                host_index: int, replicas: int = REPLICAS) -> list[int]:
    """The actor gids host ``host_index`` of ``num_hosts`` owns — the
    supervisor-facing entry point (canonical tokens, one call)."""
    tokens = host_tokens(num_hosts)
    return assign_fleet(fleet_size, tokens, replicas)[tokens[host_index]]
