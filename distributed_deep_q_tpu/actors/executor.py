"""Autoscaler executor — the acting half of the control loop (ISSUE 20).

``actors/autoscaler.py`` closed the sensing loop: health verdicts in,
lineage-traceable ``Decision``s out. Until now nobody acted on them —
the scaler moved ``autoscale/target_*`` gauges and the fleet stayed
put. ``ScaleExecutor`` consumes actor-dimension decisions and drives
the ``ActorSupervisor``'s spawn/retire machinery to make the fleet
MATCH the target, with the guard rails a process-touching control loop
needs:

- **Rate limit.** At most one applied action per ``rate_limit_s`` —
  a floor on top of the autoscaler's own per-dimension cooldown, so a
  burst of decisions (e.g. after a cooldown expiry) cannot churn the
  fleet faster than spawned actors can come up.
- **Dry run.** ``dry_run=True`` walks the whole path — selection,
  rate limiting, findings — without touching a process; every finding
  says so (``dry_run: 1``), so an operator can audit what the loop
  WOULD do before arming it.
- **Graceful retirement.** A shrink picks the highest-id actor, waits
  up to ``drain_s`` for its replay flush seq to go quiet (two stable
  polls — an in-flight flush completes and bumps the seq), terminates
  it through the supervisor's ``retire`` (counted separately from
  crash-kill escalations), and finally evicts the actor's exactly-once
  dedup stamp from the replay server so scale-down churn cannot grow
  the ``(actor_id, flush_seq)`` map unboundedly.
- **Rollback.** A grow is provisional: if the new actor has not
  heartbeated within ``spawn_grace_s`` the executor reaps it and
  releases the slot — a decision cannot leak half-alive processes.
- **Lineage.** Every applied (or skipped) action is a JSONL finding
  under ``autoscale/applied`` naming the triggering decision's rule,
  and ``autoscale/applied_actors`` rides next to the scaler's
  ``autoscale/target_actors`` gauge — ``telemetry_report --strict``
  fails a run where the two disagree at the end or an applied action
  lost its provenance.

Inference-dimension decisions have no executor yet (replicating the
serving plane is a topology change, not a process start) — they are
acknowledged with an explicit skip finding rather than dropped.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable

from distributed_deep_q_tpu.actors.autoscaler import Decision

log = logging.getLogger(__name__)

__all__ = ["ScaleExecutor"]


class ScaleExecutor:
    """Applies actor-dimension ``Decision``s through an
    ``ActorSupervisor``-shaped object (``fleet_size``/``actor_ids``/
    ``grow``/``retire``/``reap_actor``).

    ``heartbeat_ok(actor_id)`` reports whether a grown actor has made
    contact since its spawn (wired to the replay server's ``last_seen``
    map); ``stream_seq(actor_id)`` reads the actor's replay flush seq
    for the retirement drain; ``retire_stream(actor_id)`` evicts the
    dedup stamp after a drain. All three default to inert stubs so the
    executor stays testable without a live RPC plane.
    """

    def __init__(self, sup, *, rate_limit_s: float = 5.0,
                 drain_s: float = 5.0, spawn_grace_s: float = 20.0,
                 dry_run: bool = False,
                 heartbeat_ok: Callable[[int], bool] | None = None,
                 stream_seq: Callable[[int], int] | None = None,
                 retire_stream: Callable[[int], Any] | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self.sup = sup
        self.rate_limit_s = max(float(rate_limit_s), 0.0)
        self.drain_s = max(float(drain_s), 0.0)
        self.spawn_grace_s = max(float(spawn_grace_s), 0.0)
        self.dry_run = bool(dry_run)
        self._heartbeat_ok = heartbeat_ok or (lambda i: True)
        self._stream_seq = stream_seq or (lambda i: -1)
        self._retire_stream = retire_stream or (lambda i: None)
        self._clock = clock
        # mutable executor state, one lock: counters the gauges export,
        # the rate-limit stamp, and grows still inside their grace window
        self._ex_lock = threading.Lock()
        self._ex_counts = {"applied_actions": 0, "rollbacks": 0,
                           "retirements": 0, "rate_limited": 0,
                           "skipped": 0}
        self._ex_last_apply = -1e18
        self._ex_pending_grows: dict[int, float] = {}  # actor_id → t_spawn

    # -- the apply path ------------------------------------------------------

    def apply(self, decisions: list[Decision]) -> list[dict[str, Any]]:
        """Act on a tick's decisions; returns one finding dict per
        action taken, skipped, or rolled back — the supervisor logs the
        list under ``autoscale/applied``. Rollback checks run every
        call, so a grace-window miss surfaces even on decision-free
        ticks."""
        findings = self._check_rollbacks()
        for d in decisions or ():
            if not d.action.endswith("_actors"):
                findings.append(self._skip(
                    d, "no executor for the inference dimension"))
                continue
            now = self._clock()
            with self._ex_lock:
                limited = now - self._ex_last_apply < self.rate_limit_s
                if limited:
                    self._ex_counts["rate_limited"] += 1
                else:
                    self._ex_last_apply = now
            if limited:
                findings.append(self._skip(d, "rate limited"))
                continue
            if d.action.startswith("grow"):
                findings.append(self._grow(d))
            else:
                findings.append(self._shrink(d))
        return findings

    def _finding(self, d: Decision, action: str, applied: bool,
                 reason: str = "", actor_id: int = -1) -> dict[str, Any]:
        return {"action": action, "rule": d.rule, "decision_t": d.t,
                "from_n": d.from_n, "to_n": d.to_n,
                "actor_id": actor_id, "applied": int(applied),
                "dry_run": int(self.dry_run), "reason": reason,
                "t": self._clock()}

    def _skip(self, d: Decision, reason: str) -> dict[str, Any]:
        with self._ex_lock:
            self._ex_counts["skipped"] += 1
        return self._finding(d, "skip", False, reason)

    def _grow(self, d: Decision) -> dict[str, Any]:
        if self.sup.fleet_size() >= d.to_n:
            return self._skip(d, "fleet already at or above target")
        if self.dry_run:
            return self._finding(d, "grow", False, "dry run")
        i = self.sup.grow()
        with self._ex_lock:
            self._ex_counts["applied_actions"] += 1
            self._ex_pending_grows[i] = self._clock()
        log.info("autoscale executor: grew actor %d (rule %s)", i, d.rule)
        return self._finding(d, "grow", True, actor_id=i)

    def _shrink(self, d: Decision) -> dict[str, Any]:
        ids = self.sup.actor_ids()
        if len(ids) <= d.to_n or not ids:
            return self._skip(d, "fleet already at or below target")
        i = ids[-1]  # retire the highest id: boot actors live longest
        if self.dry_run:
            return self._finding(d, "retire", False, "dry run", actor_id=i)
        self._drain(i)
        if not self.sup.retire(i):
            return self._skip(d, f"actor {i} vanished before retirement")
        # the stamp eviction AFTER terminate: the actor can no longer
        # send, so the (actor_id, flush_seq) entry is provably dead
        try:
            self._retire_stream(i)
        except Exception as e:  # noqa: BLE001 — eviction is hygiene,
            # never worth failing the scale action over
            log.warning("retire_stream(%d) failed: %s: %s",
                        i, type(e).__name__, e)
        with self._ex_lock:
            self._ex_counts["applied_actions"] += 1
            self._ex_counts["retirements"] += 1
            self._ex_pending_grows.pop(i, None)
        log.info("autoscale executor: retired actor %d (rule %s)", i, d.rule)
        return self._finding(d, "retire", True, actor_id=i)

    def _drain(self, i: int) -> None:
        """Wait (bounded by ``drain_s``) for the actor's replay flush
        seq to hold still across two polls — an in-flight flush lands
        and bumps the seq; quiet means nothing is mid-wire."""
        deadline = self._clock() + self.drain_s
        try:
            last = self._stream_seq(i)
        except Exception:  # noqa: BLE001 — a dead plane means no drain
            return
        while self._clock() < deadline:
            time.sleep(min(0.2, self.drain_s or 0.2))
            try:
                cur = self._stream_seq(i)
            except Exception:  # noqa: BLE001
                return
            if cur == last:
                return
            last = cur

    def _check_rollbacks(self) -> list[dict[str, Any]]:
        """Reap grown actors that missed their spawn-grace heartbeat
        window and release their slots."""
        now = self._clock()
        with self._ex_lock:
            due = [i for i, t0 in self._ex_pending_grows.items()
                   if now - t0 >= self.spawn_grace_s]
            fresh = [i for i in self._ex_pending_grows if i not in due]
        out: list[dict[str, Any]] = []
        for i in due:
            if self._heartbeat_ok(i):
                with self._ex_lock:
                    self._ex_pending_grows.pop(i, None)
                continue
            self.sup.reap_actor(i)
            with self._ex_lock:
                self._ex_pending_grows.pop(i, None)
                self._ex_counts["rollbacks"] += 1
            log.warning("autoscale executor: rolled back actor %d "
                        "(no heartbeat within %.0fs)", i, self.spawn_grace_s)
            out.append({"action": "rollback", "rule": "spawn_grace",
                        "decision_t": 0.0, "from_n": 0, "to_n": 0,
                        "actor_id": i, "applied": 1,
                        "dry_run": int(self.dry_run),
                        "reason": "no heartbeat within spawn grace",
                        "t": now})
        # actors that heartbeated early graduate out of the pending set
        for i in fresh:
            if self._heartbeat_ok(i):
                with self._ex_lock:
                    self._ex_pending_grows.pop(i, None)
        return out

    # -- export --------------------------------------------------------------

    def gauges(self) -> dict[str, float]:
        out = {"autoscale/applied_actors": float(self.sup.fleet_size())}
        with self._ex_lock:
            for k, v in self._ex_counts.items():
                out[f"autoscale/{k}"] = float(v)
        return out
