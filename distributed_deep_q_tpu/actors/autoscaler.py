"""Health-driven autoscaler — the control loop on the PR 12 health plane.

PR 12 reduced fleet state to one machine-readable ``HealthVerdict``;
until now an SLO burn was just a log line. This module closes the loop:
``Autoscaler.observe(verdict)`` consumes the verdict's findings — rule
name, key, value/target, fast/slow burn rates — and emits grow/shrink
``Decision``s for actor and inference capacity.

The mapping is deliberately small and legible (the README table is
generated from these tuples):

- ingest pressure (``ingest_shed``, ``credit_starvation``,
  ``flush_p99``, ``staged_growth``, ``ingest_collapse``) or a lost
  member (``member_unreachable``) → SHRINK the actor fleet toward
  ``min_actors``: fewer producers protect the surviving ingest path
  while the fleet heals.
- inference pressure (``infer_latency``, ``infer_queue_growth``,
  ``infer_shed``) → GROW inference capacity toward ``max_inference``.
- a sustained-ok streak (``recover_ticks`` consecutive ok verdicts) →
  GROW actors back toward ``max_actors`` and relax inference toward
  ``min_inference`` (rule name ``capacity_recovered``).

Two dampers stop decision flapping, mirroring the hysteresis already
inside the health rules themselves:

- per-dimension COOLDOWN: after any decision on a dimension, further
  decisions on it are blocked for ``cooldown_s`` (counted in
  ``autoscale/cooldown_blocked``).
- recovery HYSTERESIS: growth requires ``recover_ticks`` consecutive
  ok verdicts; one degraded tick resets the streak.

Every decision is lineage-traceable: ``Decision.to_jsonable()`` names
the rule and carries the exact burn numbers that triggered it, and the
supervisor writes the list into the run JSONL under
``autoscale/decision`` — ``telemetry_report --strict`` fails any run
where a decision fired without that provenance.

The scaler only DECIDES; executing a decision is the operator's (or the
churn harness's) job — the same boundary the health plane draws between
verdict and remediation.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass
from typing import Any

SHRINK_ACTOR_RULES = ("ingest_shed", "credit_starvation", "flush_p99",
                      "staged_growth", "ingest_collapse",
                      "member_unreachable")
GROW_INFERENCE_RULES = ("infer_latency", "infer_queue_growth", "infer_shed")
RECOVERY_RULE = "capacity_recovered"


def _num(v: Any) -> float:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return 0.0
    return f if math.isfinite(f) else 0.0


@dataclass(frozen=True)
class Decision:
    """One grow/shrink decision with full provenance."""

    action: str      # grow_actors | shrink_actors | grow_inference | ...
    rule: str        # health rule (or RECOVERY_RULE) that triggered it
    key: str         # metric key the rule watched ("" for recovery)
    member: str      # fleet member the finding came from ("" if fleet-wide)
    value: float     # observed value / streak length
    target: float    # rule target / required streak
    burn_fast: float
    burn_slow: float
    from_n: int
    to_n: int
    t: float

    def to_jsonable(self) -> dict[str, Any]:
        return {
            "action": self.action, "rule": self.rule, "key": self.key,
            "member": self.member, "value": self.value,
            "target": self.target, "burn_fast": self.burn_fast,
            "burn_slow": self.burn_slow, "from_n": self.from_n,
            "to_n": self.to_n, "t": self.t,
        }


class Autoscaler:
    """Verdict → capacity decisions with hysteresis and cooldown.

    Thread-safe: all state moves under ``_as_lock`` (the supervisor's
    health tick calls ``observe`` while gauge readers race it)."""

    def __init__(self, min_actors: int = 1, max_actors: int = 1,
                 min_inference: int = 0, max_inference: int = 0,
                 step: int = 1, cooldown_s: float = 30.0,
                 recover_ticks: int = 3):
        if min_actors > max_actors:
            raise ValueError("min_actors exceeds max_actors")
        if min_inference > max_inference:
            raise ValueError("min_inference exceeds max_inference")
        self.min_actors = int(min_actors)
        self.max_actors = int(max_actors)
        self.min_inference = int(min_inference)
        self.max_inference = int(max_inference)
        self.step = max(1, int(step))
        self.cooldown_s = float(cooldown_s)
        self.recover_ticks = max(1, int(recover_ticks))
        # RLock: the decide/cooldown helpers re-acquire under observe()
        self._as_lock = threading.RLock()
        # start at full capacity: the boot fleet IS max until the health
        # plane says otherwise
        self._as_target_actors = self.max_actors
        self._as_target_inference = self.min_inference
        self._as_ok_streak = 0
        self._as_last_at = {"actors": float("-inf"),
                            "inference": float("-inf")}
        self._as_counts = {"decisions": 0, "grow": 0, "shrink": 0,
                           "cooldown_blocked": 0}

    # -- internals (call with _as_lock held) --------------------------------

    def _cooled(self, dim: str, t: float) -> bool:
        with self._as_lock:
            if t - self._as_last_at[dim] >= self.cooldown_s:
                return True
            self._as_counts["cooldown_blocked"] += 1
            return False

    def _decide(self, dim: str, action: str, to_n: int, finding,
                streak: int, t: float) -> Decision:
        with self._as_lock:
            self._as_last_at[dim] = t
            self._as_counts["decisions"] += 1
            self._as_counts["grow" if action.startswith("grow") else
                            "shrink"] += 1
            from_n = (self._as_target_actors if dim == "actors"
                      else self._as_target_inference)
            if dim == "actors":
                self._as_target_actors = to_n
            else:
                self._as_target_inference = to_n
        if finding is None:  # recovery path: provenance is the streak
            return Decision(action=action, rule=RECOVERY_RULE, key="",
                            member="", value=float(streak),
                            target=float(self.recover_ticks),
                            burn_fast=0.0, burn_slow=0.0,
                            from_n=from_n, to_n=to_n, t=t)
        return Decision(action=action, rule=finding.rule,
                        key=finding.key, member=finding.member or "",
                        value=_num(finding.value),
                        target=_num(finding.target),
                        burn_fast=_num(finding.burn_fast),
                        burn_slow=_num(finding.burn_slow),
                        from_n=from_n, to_n=to_n, t=t)

    # -- public surface -----------------------------------------------------

    def observe(self, verdict, t: float | None = None) -> list[Decision]:
        """Fold one fleet verdict into the targets; returns the
        decisions (possibly empty) this tick produced."""
        t = time.monotonic() if t is None else float(t)
        findings = list(getattr(verdict, "findings", ()) or ())
        shrink_f = next((f for f in findings
                         if f.rule in SHRINK_ACTOR_RULES), None)
        infer_f = next((f for f in findings
                        if f.rule in GROW_INFERENCE_RULES), None)
        out: list[Decision] = []
        with self._as_lock:
            if getattr(verdict, "ok", False):
                self._as_ok_streak += 1
            else:
                self._as_ok_streak = 0
            recovered = self._as_ok_streak >= self.recover_ticks
            # actor dimension
            if shrink_f is not None:
                to_n = max(self.min_actors,
                           self._as_target_actors - self.step)
                if to_n < self._as_target_actors and self._cooled(
                        "actors", t):
                    out.append(self._decide("actors", "shrink_actors",
                                            to_n, shrink_f, 0, t))
            elif recovered and self._as_target_actors < self.max_actors:
                to_n = min(self.max_actors,
                           self._as_target_actors + self.step)
                if self._cooled("actors", t):
                    out.append(self._decide("actors", "grow_actors", to_n,
                                            None, self._as_ok_streak, t))
            # inference dimension
            if infer_f is not None:
                to_n = min(self.max_inference,
                           self._as_target_inference + self.step)
                if to_n > self._as_target_inference and self._cooled(
                        "inference", t):
                    out.append(self._decide(
                        "inference", "grow_inference", to_n, infer_f,
                        0, t))
            elif recovered and \
                    self._as_target_inference > self.min_inference:
                to_n = max(self.min_inference,
                           self._as_target_inference - self.step)
                if self._cooled("inference", t):
                    out.append(self._decide(
                        "inference", "shrink_inference", to_n, None,
                        self._as_ok_streak, t))
        return out

    def targets(self) -> tuple[int, int]:
        with self._as_lock:
            return self._as_target_actors, self._as_target_inference

    def gauges(self) -> dict[str, float]:
        """``autoscale/*`` gauges for the supervisor's metrics tick."""
        with self._as_lock:
            return {
                "autoscale/target_actors": float(self._as_target_actors),
                "autoscale/target_inference":
                    float(self._as_target_inference),
                "autoscale/decisions":
                    float(self._as_counts["decisions"]),
                "autoscale/grow": float(self._as_counts["grow"]),
                "autoscale/shrink": float(self._as_counts["shrink"]),
                "autoscale/cooldown_blocked":
                    float(self._as_counts["cooldown_blocked"]),
            }
