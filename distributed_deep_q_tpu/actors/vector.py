"""Vectorized acting plane: N stacked envs behind one batched step.

The Sebulba half of the Podracer split (PAPERS.md arXiv:2104.06272):
instead of one Python process per environment, one process drives a
``VectorEnv`` — N copies of any ``game.py`` env stepped in a fixed order
behind a single ``reset()/step(actions)`` — and cuts bucket-sized
observation batches into the PR 9 ``infer`` verb, one RPC per wall tick
instead of N. The contract that makes this safe to adopt is BITWISE
parity: a ``VectorEnv`` over envs ``e_0..e_{N-1}`` produces exactly the
frames/rewards/dones that stepping each ``e_j`` sequentially would, and
``VectorFrameStacker`` row ``j`` is byte-identical to a per-env
``FrameStacker`` — same seeds → same actions → same transitions
(``tests/test_vector_env.py`` pins this on mlp and nature_cnn torsos).

Auto-reset semantics mirror the supervisor's single-env loop: the actor
appends the PRE-step frame to its chunk and, on episode end, discards
the post-step frame in favor of the reset frame — so ``step`` returns
the NEW episode's first frame for rows whose episode just ended, and
the per-row done/over flags still describe the step that ended it.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Sequence

import numpy as np

from distributed_deep_q_tpu.actors.game import Env, make_envs


class VectorEnv:
    """N independent ``game.py`` envs behind one batched step.

    Envs are stepped in index order (each env owns its own rng, so the
    order is only about determinism of the Python loop, not coupling).
    ``step`` auto-resets: for rows where the episode ended (``over``),
    the returned frame is the NEW episode's first frame — exactly the
    frame the single-env actor loop would hold after its
    ``env.reset()`` call.
    """

    def __init__(self, envs: Sequence[Env]):
        if not envs:
            raise ValueError("VectorEnv needs at least one env")
        self.envs = list(envs)
        self.num_envs = len(self.envs)
        e0 = self.envs[0]
        self.num_actions = e0.num_actions
        self.obs_shape = tuple(e0.obs_shape)
        self.obs_dtype = e0.obs_dtype
        for e in self.envs[1:]:
            if (e.num_actions != self.num_actions
                    or tuple(e.obs_shape) != self.obs_shape):
                raise ValueError(
                    "VectorEnv requires a homogeneous action/obs space: "
                    f"{(e.num_actions, tuple(e.obs_shape))} vs "
                    f"{(self.num_actions, self.obs_shape)}")

    def reset(self) -> np.ndarray:
        return np.stack([e.reset() for e in self.envs])

    def step(self, actions: np.ndarray):
        """-> (frames [N, *obs_shape], rewards f32[N], dones bool[N],
        overs bool[N]); frames for ``over`` rows are reset frames."""
        n = self.num_envs
        frames = np.empty((n,) + self.obs_shape, self.obs_dtype)
        rewards = np.empty(n, np.float32)
        dones = np.empty(n, bool)
        overs = np.empty(n, bool)
        for j, env in enumerate(self.envs):
            f, r, d, o = env.step(int(actions[j]))
            if o:
                f = env.reset()
            frames[j] = f
            rewards[j], dones[j], overs[j] = r, d, o
        return frames, rewards, dones, overs


class VectorFrameStacker:
    """``FrameStacker`` generalized to a batch axis: [N, H, W, stack].

    Row ``j`` evolves byte-identically to a standalone ``FrameStacker``
    fed env ``j``'s frames (same roll axis, same zero-fill reset), so a
    vectorized actor's observations match the per-env fleet bit-for-bit.
    """

    def __init__(self, num_envs: int, frame_shape: tuple[int, ...],
                 stack: int):
        self._buf = np.zeros(
            (num_envs,) + tuple(frame_shape) + (stack,), np.uint8)

    def reset(self, frames: np.ndarray) -> np.ndarray:
        self._buf[:] = 0
        self._buf[..., -1] = frames
        return self._buf

    def reset_row(self, row: int, frame: np.ndarray) -> None:
        self._buf[row] = 0
        self._buf[row, ..., -1] = frame

    def push(self, frames: np.ndarray) -> np.ndarray:
        self._buf = np.roll(self._buf, -1, axis=-1)
        self._buf[..., -1] = frames
        return self._buf

    @property
    def obs(self) -> np.ndarray:
        return self._buf


class VectorStepLatencyEnv:
    """Batched counterpart of ``StepLatencyEnv``: times the WHOLE vector
    tick (all N envs), not just env 0 — wrapping env 0 of a stack would
    silently report 1/N of the acting cost. ``drain_step_ms`` returns
    whole-tick samples; callers divide by ``num_envs`` for the per-env
    amortized figure."""

    def __init__(self, env: VectorEnv, maxlen: int = 512):
        self._env = env
        self._step_ms: deque = deque(maxlen=maxlen)

    def step(self, actions: np.ndarray):
        t0 = time.perf_counter()
        out = self._env.step(actions)
        self._step_ms.append(1e3 * (time.perf_counter() - t0))
        return out

    def reset(self) -> np.ndarray:
        return self._env.reset()

    def drain_step_ms(self) -> list[float]:
        out = list(self._step_ms)
        self._step_ms.clear()
        return out

    def __getattr__(self, name: str):
        return getattr(self._env, name)


def make_vector_env(env_cfgs, seeds: Sequence[int],
                    latency: bool = False):
    """Build a ``VectorEnv`` from per-row (EnvConfig, seed) pairs.

    ``env_cfgs`` is either one EnvConfig (replicated) or a sequence of
    per-row configs (the multi-game fleet case — ``env_for_actor``
    output per global id). Seeding stays the fleet's discipline: caller
    passes exactly the seeds the per-env processes would have used.
    """
    venv = VectorEnv(make_envs(env_cfgs, seeds))
    return VectorStepLatencyEnv(venv) if latency else venv


def select_actions(obs: np.ndarray, rngs: Sequence[np.random.Generator],
                   epsilons: Sequence[float], num_actions: int,
                   greedy_fn: Callable[[np.ndarray], np.ndarray],
                   ) -> np.ndarray:
    """Per-env ε-greedy over a batched greedy policy.

    The ε draws replicate the single-env actor loop exactly — env j's
    rng draws ``random()`` and (on the explore branch) ``integers`` in
    row order, consuming the same stream positions as N sequential
    actors would. Greedy rows are gathered into ONE ``greedy_fn`` call
    (batched local forward or one remote ``infer`` RPC); row k of its
    result must equal the single-row forward of row k's obs, which the
    parity tests pin for both torsos.
    """
    n = len(rngs)
    actions = np.empty(n, np.int64)
    greedy: list[int] = []
    for j in range(n):
        if rngs[j].random() < float(epsilons[j]):
            actions[j] = int(rngs[j].integers(num_actions))
        else:
            greedy.append(j)
    if greedy:
        picked = np.asarray(greedy_fn(obs[np.asarray(greedy)]))
        for k, j in enumerate(greedy):
            actions[j] = int(picked[k])
    return actions


class VectorActing:
    """The RPC-free core of the vectorized actor loop.

    Owns the stacked env, the batched frame stacker, and the per-env
    ε-greedy rng streams; each ``tick(greedy_fn)`` selects N actions,
    steps the stack once, and returns the per-env transition records
    the supervisor flushes down the wire. Factored out of the
    supervisor so the bitwise-parity tests (and the bench) can drive
    the exact production tick without sockets.
    """

    def __init__(self, env, stack: int,
                 rngs: Sequence[np.random.Generator],
                 epsilons: Sequence[float]):
        if env.obs_dtype != np.uint8:
            raise ValueError("vector acting is the pixel path "
                             f"(uint8 frames), got {env.obs_dtype}")
        self.env = env
        self.num_envs = env.num_envs
        if len(rngs) != self.num_envs or len(epsilons) != self.num_envs:
            raise ValueError("need one rng and one epsilon per env")
        self.rngs = list(rngs)
        self.epsilons = [float(e) for e in epsilons]
        self.stacker = VectorFrameStacker(
            self.num_envs, env.obs_shape, stack)
        self.frames = env.reset()
        self.obs = self.stacker.reset(self.frames)
        self.ep_return = np.zeros(self.num_envs, np.float64)
        self.auto_resets = 0
        # (row, episode return) pairs, drained by the supervisor so each
        # row's returns ship on that row's replay stream
        self.completed: list[tuple[int, float]] = []

    def tick(self, greedy_fn):
        """One wall tick: N actions, one batched env step.

        Returns ``(frames, actions, rewards, dones, overs)`` where
        ``frames`` is the PRE-step frame batch — exactly what the
        single-env loop appends to its chunk before stepping.
        """
        actions = select_actions(self.obs, self.rngs, self.epsilons,
                                 self.env.num_actions, greedy_fn)
        pre = self.frames
        nxt, rewards, dones, overs = self.env.step(actions)
        self.frames = nxt
        self.obs = self.stacker.push(nxt)
        self.ep_return += rewards
        for j in np.flatnonzero(overs):
            # env auto-reset already returned the new episode's first
            # frame for this row; re-anchor its stack the same way the
            # single-env loop does (push-then-reset ≡ reset: the row is
            # overwritten wholesale)
            self.stacker.reset_row(int(j), nxt[j])
            self.completed.append((int(j), float(self.ep_return[j])))
            self.ep_return[j] = 0.0
            self.auto_resets += 1
        return pre, actions, rewards, dones, overs

    def drain_completed(self) -> list[tuple[int, float]]:
        out = self.completed
        self.completed = []
        return out
