"""Single-process training loop — config 1 (CartPole smoke) and the
in-process Atari path (SURVEY.md §7.2 step 1: the minimum end-to-end slice).

One process hosts actor + replay + learner; the distributed topology
(actors over RPC → replay service → mesh learner) lives in ``rpc/`` and
``actors/supervisor.py`` and reuses the same Solver and replay components.
"""

from __future__ import annotations

import numpy as np

from distributed_deep_q_tpu.actors.game import (
    FrameStacker, NStepAccumulator, make_env)
from distributed_deep_q_tpu.config import Config
from distributed_deep_q_tpu.metrics import Metrics, MovingAverage
from distributed_deep_q_tpu.replay.device_ring import DeviceFrameReplay
from distributed_deep_q_tpu.replay.prioritized import maybe_prioritize
from distributed_deep_q_tpu.replay.replay_memory import FrameStackReplay, ReplayMemory
from distributed_deep_q_tpu.solver import Solver


def epsilon_at(step: int, cfg) -> float:
    """Linear ε anneal (Nature-DQN style single-actor schedule)."""
    frac = min(step / max(cfg.eps_decay_steps, 1), 1.0)
    return cfg.eps_start + frac * (cfg.eps_end - cfg.eps_start)


def evaluate(solver: Solver, cfg: Config, episodes: int | None = None,
             seed: int = 10_000) -> float:
    """Greedy-policy rollouts (ε=eval_eps) → mean episode return
    (SURVEY §3.5 [M])."""
    env = make_env(cfg.env, seed=seed)
    rng = np.random.default_rng(seed)
    episodes = episodes or cfg.train.eval_episodes
    pixel_env = env.obs_dtype == np.uint8
    stacker = FrameStacker(env.obs_shape, cfg.env.stack) if pixel_env else None
    returns = []
    for _ in range(episodes):
        obs, ep_ret, over = env.reset(), 0.0, False
        if stacker:
            obs = stacker.reset(obs)
        while not over:
            a = solver.act(obs, cfg.actors.eval_eps, rng)
            frame, r, _, over = env.step(a)
            obs = stacker.push(frame) if stacker else frame
            ep_ret += r
        returns.append(ep_ret)
    return float(np.mean(returns))


def train_single_process(cfg: Config, metrics: Metrics | None = None,
                         log_every: int = 1_000) -> dict:
    """Run config-1-style training; returns final summary metrics."""
    metrics = metrics or Metrics()
    env = make_env(cfg.env, seed=cfg.train.seed)
    cfg.net.num_actions = env.num_actions
    obs_dim = int(np.prod(env.obs_shape))
    solver = Solver(cfg, obs_dim=obs_dim)
    rng = np.random.default_rng(cfg.train.seed)

    pixel_env = env.obs_dtype == np.uint8
    if pixel_env:
        if cfg.replay.device_resident:
            # TPU-first data path: frames live in HBM, the step gathers
            # stacks on device; PER (when enabled) is handled per shard
            # inside DeviceFrameReplay
            replay = DeviceFrameReplay(
                cfg.replay, solver.mesh, env.obs_shape, cfg.env.stack,
                cfg.train.gamma, seed=cfg.train.seed,
                write_chunk=cfg.replay.write_chunk)
        else:
            replay = maybe_prioritize(FrameStackReplay(
                cfg.replay.capacity, env.obs_shape, cfg.env.stack,
                cfg.replay.n_step, cfg.train.gamma, seed=cfg.train.seed),
                cfg.replay, seed=cfg.train.seed)
        stacker = FrameStacker(env.obs_shape, cfg.env.stack)
    else:
        replay = maybe_prioritize(ReplayMemory(
            cfg.replay.capacity, env.obs_shape, np.float32,
            seed=cfg.train.seed), cfg.replay, seed=cfg.train.seed)
        nstep = NStepAccumulator(cfg.replay.n_step, cfg.train.gamma)

    frame = env.reset()
    obs = stacker.reset(frame) if pixel_env else frame
    ep_ret, ep_returns = 0.0, MovingAverage(100)
    summary: dict = {}
    pending = None  # (index, td_abs, sampled_at) awaiting PER write-back
    gsteps = 0

    for t in range(1, cfg.train.total_steps + 1):
        eps = epsilon_at(t, cfg.actors)
        a = solver.act(obs, eps, rng)
        next_frame, r, done, over = env.step(a)
        ep_ret += r

        if pixel_env:
            # frame (pre-action), action, reward, done; boundary marks any
            # episode end incl. truncation so stacks/windows never cross it
            replay.add(frame, a, r, done, boundary=over)
            frame = next_frame
            obs = stacker.push(frame)
        else:
            for tr in nstep.push(obs, a, r, next_frame, done):
                replay.add(*tr)
            obs = next_frame
        metrics.count("env_steps")

        if over:
            if not pixel_env and not done:
                # time-limit truncation: flush the n-step tail with bootstrap
                # instead of discarding the end-of-episode transitions
                for tr in nstep.flush_truncated(next_frame):
                    replay.add(*tr)
            ep_returns.add(ep_ret)
            ep_ret = 0.0
            frame = env.reset()
            if pixel_env:
                obs = stacker.reset(frame)
            else:
                obs = frame
                nstep.reset()

        if (replay.ready(cfg.replay.learn_start)
                and t % cfg.train.train_every == 0):
            batch = replay.sample(cfg.replay.batch_size)
            sampled_at = batch.pop("_sampled_at", replay.steps_added)
            if isinstance(replay, DeviceFrameReplay):
                m = solver.train_step_from_ring(replay.ring, batch)
            else:
                m = solver.train_step(batch)
            gsteps += 1
            if replay.prioritized:
                # one-step-delayed priority write-back: materializing |TD|
                # for the *previous* step is free by now (its device work is
                # done), so the fresh step is never host-blocked
                if pending is not None:
                    replay.update_priorities(pending[0],
                                             np.asarray(pending[1]),
                                             sampled_at=pending[2])
                pending = (m["index"], m["td_abs"], sampled_at)
            metrics.count("grad_steps")
            # host-side counter: reading solver.step would sync on the
            # just-dispatched device step every iteration
            if gsteps % log_every == 0:
                summary = {
                    "loss": float(m["loss"]), "q_mean": float(m["q_mean"]),
                    "return_avg100": ep_returns.value, "epsilon": eps,
                    "grad_steps_per_s": metrics.rate("grad_steps"),
                    "env_steps_per_s": metrics.rate("env_steps"),
                }
                metrics.log(solver.step, **summary)

        if (cfg.train.eval_every and t % cfg.train.eval_every == 0):
            metrics.log(solver.step, eval_return=evaluate(solver, cfg))

    summary["final_return_avg100"] = ep_returns.value
    summary["eval_return"] = evaluate(solver, cfg)
    summary["solver"] = solver
    return summary
