"""Single-process training loop — config 1 (CartPole smoke) and the
in-process Atari path (SURVEY.md §7.2 step 1: the minimum end-to-end slice).

One process hosts actor + replay + learner; the distributed topology
(actors over RPC → replay service → mesh learner) lives in ``rpc/`` and
``actors/supervisor.py`` and reuses the same Solver and replay components.
"""

from __future__ import annotations

import os

import jax
import numpy as np

from distributed_deep_q_tpu.actors.game import (
    FrameStacker, NStepAccumulator, make_env)
from distributed_deep_q_tpu.config import Config
from distributed_deep_q_tpu.metrics import Metrics, MovingAverage
from distributed_deep_q_tpu.profiling import (
    StepTimer, TraceWindow, start_profiler_server)
from distributed_deep_q_tpu.replay.device_ring import DeviceFrameReplay
from distributed_deep_q_tpu.replay.prioritized import maybe_prioritize
from distributed_deep_q_tpu.replay.replay_memory import FrameStackReplay, ReplayMemory
from distributed_deep_q_tpu.solver import Solver
from distributed_deep_q_tpu.utils.checkpoint import maybe_checkpointer


def epsilon_at(step: int, cfg) -> float:
    """Linear ε anneal (Nature-DQN style single-actor schedule)."""
    frac = min(step / max(cfg.eps_decay_steps, 1), 1.0)
    return cfg.eps_start + frac * (cfg.eps_end - cfg.eps_start)


def evaluate(solver: Solver, cfg: Config, episodes: int | None = None,
             seed: int = 10_000) -> float:
    """Greedy-policy rollouts (ε=eval_eps) → mean episode return
    (SURVEY §3.5 [M])."""
    env = make_env(cfg.env, seed=seed)
    rng = np.random.default_rng(seed)
    episodes = episodes or cfg.train.eval_episodes
    pixel_env = env.obs_dtype == np.uint8
    stacker = FrameStacker(env.obs_shape, cfg.env.stack) if pixel_env else None
    returns = []
    for _ in range(episodes):
        obs, ep_ret, over = env.reset(), 0.0, False
        if stacker:
            obs = stacker.reset(obs)
        while not over:
            a = solver.act(obs, cfg.actors.eval_eps, rng)
            frame, r, _, over = env.step(a)
            obs = stacker.push(frame) if stacker else frame
            ep_ret += r
        returns.append(ep_ret)
    return float(np.mean(returns))


def evaluate_per_game(solver, cfg: Config, episodes: int | None = None,
                      seed: int = 10_000, recurrent: bool = False,
                      ) -> dict[str, float]:
    """Greedy eval on every configured game (config 4 multi-game fleets):
    ``{game_id: mean return}``; single-game configs return one entry."""
    import dataclasses

    fn = evaluate_recurrent if recurrent else evaluate
    out = {}
    for g in (cfg.env.games or (cfg.env.id,)):
        gcfg = cfg.replace(env=dataclasses.replace(cfg.env, id=g))
        out[g] = fn(solver, gcfg, episodes, seed)
    return out


def log_final_eval(solver, cfg: Config, metrics: Metrics, summary: dict,
                   recurrent: bool = False) -> float:
    """Final greedy eval across all configured games: fills ``summary``
    (``eval_return`` mean, ``eval_per_game`` when multi-game) and logs
    per-game metrics. Shared by the distributed loops."""
    per_game = evaluate_per_game(solver, cfg, recurrent=recurrent)
    summary["eval_return"] = float(np.mean(list(per_game.values())))
    if len(per_game) > 1:
        summary["eval_per_game"] = per_game
        metrics.log(cfg.train.total_steps,
                    **{f"eval_return/{g}": v for g, v in per_game.items()})
    return summary["eval_return"]


def train_single_process(cfg: Config, metrics: Metrics | None = None,
                         log_every: int = 1_000) -> dict:
    """Run config-1-style training; returns final summary metrics.

    Multi-host (config 5, SURVEY §5.8): when the process was connected via
    ``initialize_multihost``, every host runs this same loop — its own env
    (seed-offset per process) feeding its own replay shard, sampling its
    ``batch_size/process_count`` local rows into the global-mesh train step
    whose ``lax.pmean`` spans hosts. The learn gate opens only when every
    host's shard is warm (``all_processes_ready``) so no process enters the
    collective step early.
    """
    if cfg.net.kind == "r2d2":
        return train_recurrent(cfg, metrics, log_every)
    metrics = metrics or Metrics()
    # NOTE: solver/env construction initializes the JAX backend; only then
    # is process topology safe to query (probing earlier would pre-empt the
    # --backend platform selection).
    env = make_env(cfg.env, seed=cfg.train.seed)
    cfg.net.num_actions = env.num_actions
    obs_dim = int(np.prod(env.obs_shape))
    solver = Solver(cfg, obs_dim=obs_dim)
    pc, pid = jax.process_count(), jax.process_index()
    local_batch = cfg.replay.batch_size
    if pc > 1:
        from distributed_deep_q_tpu.parallel.multihost import (
            all_processes_ready, local_rows)
        if cfg.replay.batch_size % pc:
            raise ValueError(f"replay.batch_size={cfg.replay.batch_size} "
                             f"must divide across {pc} processes")
        local_batch = cfg.replay.batch_size // pc
        # decorrelate the per-host experience streams
        env = make_env(cfg.env, seed=cfg.train.seed + 131 * pid)
        if pid != 0:
            metrics = Metrics()  # file/TB sinks live on process 0 only
    rng = np.random.default_rng(cfg.train.seed + 131 * pid)

    seed = cfg.train.seed + 131 * pid
    pixel_env = env.obs_dtype == np.uint8
    if pixel_env:
        if cfg.replay.device_resident:
            if pc > 1:
                raise ValueError(
                    "replay.device_resident=True is single-controller only "
                    "(the host writes frames into a mesh-sharded HBM ring); "
                    "multi-host pixel runs need replay.device_resident=false")
            # TPU-first data path: frames live in HBM, the step gathers
            # stacks on device; PER (when enabled) is handled per shard
            # inside DeviceFrameReplay — or fully fused into the step
            # (device_per: priorities/metadata in HBM, zero host round
            # trips per step)
            if cfg.replay.prioritized and cfg.replay.device_per:
                from distributed_deep_q_tpu.replay.device_per import (
                    DevicePERFrameReplay)
                replay = DevicePERFrameReplay(
                    cfg.replay, solver.mesh, env.obs_shape, cfg.env.stack,
                    cfg.train.gamma, seed=seed,
                    write_chunk=cfg.replay.write_chunk)
            else:
                replay = DeviceFrameReplay(
                    cfg.replay, solver.mesh, env.obs_shape, cfg.env.stack,
                    cfg.train.gamma, seed=seed,
                    write_chunk=cfg.replay.write_chunk)
        else:
            replay = maybe_prioritize(FrameStackReplay(
                cfg.replay.capacity, env.obs_shape, cfg.env.stack,
                cfg.replay.n_step, cfg.train.gamma, seed=seed),
                cfg.replay, seed=seed)
        stacker = FrameStacker(env.obs_shape, cfg.env.stack)
    else:
        replay = maybe_prioritize(ReplayMemory(
            cfg.replay.capacity, env.obs_shape, np.float32,
            seed=seed), cfg.replay, seed=seed)
        nstep = NStepAccumulator(cfg.replay.n_step, cfg.train.gamma)

    frame = env.reset()
    obs = stacker.reset(frame) if pixel_env else frame
    ep_ret, ep_returns = 0.0, MovingAverage(100)
    summary: dict = {}
    from distributed_deep_q_tpu.replay.device_per import DevicePERFrameReplay
    from distributed_deep_q_tpu.solver import FusedStepStream
    fused_per = isinstance(replay, DevicePERFrameReplay)
    writeback = None
    if replay.prioritized and not fused_per:
        from distributed_deep_q_tpu.replay.prioritized import make_writeback
        writeback = make_writeback(replay, cfg.replay,
                                   to_host=None if pc == 1 else local_rows)
    learn_live = False  # latched once warm (all shards warm, multi-host)
    gsteps = 0
    best_eval, best_params = float("-inf"), None
    timer = StepTimer()
    fused_stream = (FusedStepStream(solver, replay, cfg.replay.fused_chain,
                                    timer=timer) if fused_per else None)
    # learning-dynamics plane (ISSUE 16): in-process loop folds the
    # fused chunks' returned planes into learn/* gauges at log cadence
    # (the health-plane registration is the distributed supervisor's job)
    learn_acc = None
    if cfg.train.learn_metrics and fused_per:
        from distributed_deep_q_tpu import learning
        learn_acc = learning.LearnAccumulator()
    trace = TraceWindow(cfg.train.profile_dir, cfg.train.profile_start_step,
                        cfg.train.profile_num_steps)
    if cfg.train.profile_port:
        start_profiler_server(cfg.train.profile_port)
    ckpt = maybe_checkpointer(cfg.train)
    if ckpt and cfg.train.resume and ckpt.latest_step() is not None:
        solver.state, _ = ckpt.restore(solver.state)
        gsteps = solver.step
    persist = cfg.replay.persist_path
    if persist and pc > 1:
        # per-process shard files: a shared path would race on save and
        # clone one shard's content (and RNG) onto every host on resume
        persist = f"{persist}.proc{pid}"
    if persist and cfg.train.resume and os.path.exists(persist):
        # opt-in replay persistence (SURVEY §5.4): restore the buffer's
        # exact sampling state instead of warm-refilling
        from distributed_deep_q_tpu.replay.persistence import load_replay
        load_replay(replay, persist)

    try:
        for t in range(1, cfg.train.total_steps + 1):
            eps = epsilon_at(t, cfg.actors)
            a = solver.act(obs, eps, rng)
            next_frame, r, done, over = env.step(a)
            ep_ret += r

            if pixel_env:
                # frame (pre-action), action, reward, done; boundary marks any
                # episode end incl. truncation so stacks/windows never cross it
                replay.add(frame, a, r, done, boundary=over)
                frame = next_frame
                obs = stacker.push(frame)
            else:
                for tr in nstep.push(obs, a, r, next_frame, done):
                    replay.add(*tr)
                obs = next_frame
            metrics.count("env_steps")

            if over:
                if not pixel_env and not done:
                    # time-limit truncation: flush the n-step tail with bootstrap
                    # instead of discarding the end-of-episode transitions
                    for tr in nstep.flush_truncated(next_frame):
                        replay.add(*tr)
                ep_returns.add(ep_ret)
                ep_ret = 0.0
                frame = env.reset()
                if pixel_env:
                    obs = stacker.reset(frame)
                else:
                    obs = frame
                    nstep.reset()

            if t % cfg.train.train_every == 0 and not learn_live:
                # the ready latch: single-process = local fill check;
                # multi-host = every process's shard warm (collective AND,
                # called at the same loop point on every host)
                ready = replay.ready(cfg.replay.learn_start)
                learn_live = (ready if pc == 1
                              else all_processes_ready(ready))
            if learn_live and t % cfg.train.train_every == 0:
                # learn phase: j minibatches per k env steps (SURVEY §3.1 [M]).
                # Fused path: chain up to fused_chain of the j steps into one
                # two-program dispatch (lax.scan); per-step bookkeeping below
                # reads its row of the chunk's stacked metrics.
                for j in range(cfg.train.grad_steps_per_train):
                    if fused_per:
                        # sample+train+priority-update fused on device,
                        # up to fused_chain grad steps per dispatch
                        # (FusedStepStream owns the chunk/tail/slicing)
                        m = fused_stream.next(
                            cfg.train.grad_steps_per_train - j)
                    else:
                        with timer.phase("sample"):
                            batch = replay.sample(local_batch)
                        sampled_at = batch.pop("_sampled_at",
                                               replay.steps_added)
                        with timer.phase("dispatch"):
                            if isinstance(replay, DeviceFrameReplay):
                                m = solver.train_step_from_ring(
                                    replay.ring, batch, replay.frame_shape)
                            else:
                                m = solver.train_step(batch)
                    gsteps += 1
                    timer.step_done()
                    trace.on_step(gsteps)
                    if replay.prioritized and not fused_per:
                        # pipelined priority write-back: |TD| is async-
                        # copied at dispatch and consumed ``depth`` steps
                        # later, so the learner never blocks on a D2H
                        # fetch. Multi-host: each process writes back only
                        # its own rows, into its own shard (local_rows).
                        writeback.push(m["index"], m["td_abs"], sampled_at)
                    metrics.count("grad_steps")
                    if ckpt and gsteps % cfg.train.checkpoint_every == 0:
                        ckpt.save(solver.state, extra={"env_steps": t})
                        if persist:
                            from distributed_deep_q_tpu.replay.persistence \
                                import save_replay
                            save_replay(replay, persist)
                    # host-side counter: reading solver.step would sync on the
                    # just-dispatched device step every iteration
                    if gsteps % log_every == 0:
                        timer.measure_device(m["loss"])
                        summary = {
                            "loss": float(m["loss"]),
                            "q_mean": float(m["q_mean"]),
                            "return_avg100": ep_returns.value, "epsilon": eps,
                            "grad_steps_per_s": metrics.rate("grad_steps"),
                            "env_steps_per_s": metrics.rate("env_steps"),
                        }
                        metrics.gauge("queue/replay_size", len(replay))
                        pending = getattr(replay, "pending_rows", None)
                        if pending is not None:
                            metrics.gauge("queue/staged_rows", pending())
                        if learn_acc is not None:
                            # D2H of the window's planes happens here, at
                            # log cadence — never on the step path
                            for plane in fused_stream.drain_planes():
                                learn_acc.ingest(plane)
                            for lk, lv in learn_acc.gauges().items():
                                metrics.gauge(lk, lv)
                            for lk, lv in learn_acc.hist_snapshot(
                                    ).summary(
                                    prefix="learn/td_error").items():
                                metrics.gauge(lk, lv)
                        metrics.log(solver.step, **summary, **timer.summary(),
                                    **metrics.telemetry())

            if (cfg.train.eval_every and t % cfg.train.eval_every == 0):
                ret = evaluate(solver, cfg)
                metrics.log(solver.step, eval_return=ret)
                if cfg.train.keep_best_eval and ret > best_eval:
                    best_eval = ret
                    best_params = jax.device_get(solver.state.params)

    finally:
        trace.close()
    if writeback:
        writeback.drain()  # apply the depth-queued priority tail
    summary["final_return_avg100"] = ep_returns.value
    final_ret = evaluate(solver, cfg)
    if best_params is not None and best_eval > final_ret:
        # model selection: the best-eval snapshot beats the final params;
        # restore BEFORE the final checkpoint so what's on disk is what
        # eval_return reports
        solver.state = solver.state.replace(params=jax.device_put(
            best_params, solver.learner._replicated))
        final_ret = evaluate(solver, cfg)
    if ckpt:
        ckpt.save(solver.state, extra={"env_steps": cfg.train.total_steps},
                  wait=True)
    if persist:
        from distributed_deep_q_tpu.replay.persistence import save_replay
        save_replay(replay, persist)
    summary["eval_return"] = final_ret
    summary["solver"] = solver
    return summary


# ---------------------------------------------------------------------------
# Recurrent (R2D2) single-process loop — config 5 [M]
# ---------------------------------------------------------------------------


def evaluate_recurrent(solver, cfg: Config, episodes: int | None = None,
                       seed: int = 10_000) -> float:
    """Greedy rollouts threading LSTM state through the episode."""
    env = make_env(cfg.env, seed=seed)
    rng = np.random.default_rng(seed)
    episodes = episodes or cfg.train.eval_episodes
    pixel = env.obs_dtype == np.uint8
    stacker = FrameStacker(env.obs_shape, cfg.env.stack) if pixel else None
    returns = []
    for _ in range(episodes):
        obs, ep_ret, over = env.reset(), 0.0, False
        if stacker:
            obs = stacker.reset(obs)
        carry = solver.initial_state(1)
        while not over:
            a, carry = solver.act(np.asarray(obs), carry,
                                  cfg.actors.eval_eps, rng)
            frame, r, _, over = env.step(a)
            obs = stacker.push(frame) if stacker else frame
            ep_ret += r
        returns.append(ep_ret)
    return float(np.mean(returns))


def train_recurrent(cfg: Config, metrics: Metrics | None = None,
                    log_every: int = 1_000) -> dict:
    """R2D2 loop: recurrent actor → SequenceBuilder → SequenceReplay →
    SequenceLearner. Sequence counts derive from transition-denominated
    config fields (capacity/learn_start ÷ seq_len)."""
    from distributed_deep_q_tpu.parallel.sequence_learner import SequenceSolver
    from distributed_deep_q_tpu.replay.sequence import (
        SequenceBuilder, SequenceReplay)

    metrics = metrics or Metrics()
    env = make_env(cfg.env, seed=cfg.train.seed)
    cfg.net.num_actions = env.num_actions
    obs_dim = int(np.prod(env.obs_shape))
    solver = SequenceSolver(cfg, obs_dim=obs_dim)
    rng = np.random.default_rng(cfg.train.seed)

    pixel = env.obs_dtype == np.uint8
    stacker = FrameStacker(env.obs_shape, cfg.env.stack) if pixel else None
    obs_shape = (tuple(env.obs_shape) + (cfg.env.stack,)) if pixel \
        else tuple(env.obs_shape)
    obs_dtype = np.uint8 if pixel else np.float32

    seq_len = cfg.replay.sequence_length
    seq_capacity = max(cfg.replay.capacity // seq_len, 64)
    device_seq = pixel and cfg.replay.device_resident
    if device_seq:
        # R2D2 pixel plane in HBM: frames stored once (unstacked streams),
        # [B, T+1, H, W, S] windows composed on device — kills the
        # ~36 MB/step host→device sequence-minibatch transfer
        # (replay/device_sequence.py)
        from distributed_deep_q_tpu.replay.device_sequence import (
            DeviceSequenceReplay)
        replay = DeviceSequenceReplay(
            seq_capacity, seq_len, obs_shape, solver.mesh,
            cfg.net.lstm_size, prioritized=cfg.replay.prioritized,
            alpha=cfg.replay.priority_alpha, beta0=cfg.replay.priority_beta0,
            beta_steps=cfg.replay.priority_beta_steps,
            eps=cfg.replay.priority_eps, seed=cfg.train.seed)
    else:
        replay = SequenceReplay(
            seq_capacity, seq_len, obs_shape,
            obs_dtype, cfg.net.lstm_size, prioritized=cfg.replay.prioritized,
            alpha=cfg.replay.priority_alpha, beta0=cfg.replay.priority_beta0,
            beta_steps=cfg.replay.priority_beta_steps,
            eps=cfg.replay.priority_eps, seed=cfg.train.seed)
    builder = SequenceBuilder(seq_len, cfg.replay.burn_in, obs_shape,
                              obs_dtype, cfg.net.lstm_size, cfg.train.gamma)
    learn_start_seqs = max(cfg.replay.learn_start // seq_len, 2)

    # fused chained sequence path: sampling/meta/pixels/priorities all on
    # device, chain grad steps per dispatch (sequence twin of the
    # transition path's FusedStepStream loop). Prioritized-only, same
    # gate as the transition path: the device sampler draws from the
    # priority row, so a uniform config must keep the per-step path.
    fused_seq = (device_seq and cfg.replay.device_per
                 and cfg.replay.prioritized)
    stream = None
    if fused_seq:
        from distributed_deep_q_tpu.solver import FusedStepStream
        stream = FusedStepStream(solver, replay,
                                 max(int(cfg.replay.fused_chain), 1))

    frame = env.reset()
    obs = stacker.reset(frame) if pixel else frame
    carry = solver.initial_state(1)
    ep_ret, ep_returns = 0.0, MovingAverage(100)
    summary: dict = {}
    writeback = None
    if replay.prioritized and not fused_seq:
        from distributed_deep_q_tpu.replay.prioritized import make_writeback
        writeback = make_writeback(replay, cfg.replay)
    gsteps = 0
    ckpt = maybe_checkpointer(cfg.train)
    if ckpt and cfg.train.resume and ckpt.latest_step() is not None:
        solver.state, _ = ckpt.restore(solver.state)
        gsteps = solver.step
    persist = cfg.replay.persist_path
    if persist and jax.process_count() > 1:
        if device_seq:
            # the device sequence ring is a GLOBAL mesh array: each
            # process's shard file would hold only its addressable slice,
            # and resume would reassemble a buffer whose sampling state no
            # longer matches the mesh — silent corruption. Refuse loudly.
            raise ValueError(
                "replay.persist_path is not supported with a device-"
                "resident DeviceSequenceReplay under multi-process "
                f"(process_count={jax.process_count()}); set "
                "replay.device_resident=false or drop persist_path")
        # per-process shard files (same rule as train_single_process): a
        # shared path would race on save and clone one process's state
        # onto every host on resume
        persist = f"{persist}.proc{jax.process_index()}"
    if persist and cfg.train.resume and os.path.exists(persist):
        # opt-in replay persistence (SURVEY §5.4), sequence edition:
        # restore the buffer's exact sampling state (host store or device
        # ring + device meta/priorities) instead of warm-refilling
        from distributed_deep_q_tpu.replay.persistence import load_replay
        load_replay(replay, persist)

    for t in range(1, cfg.train.total_steps + 1):
        eps = epsilon_at(t, cfg.actors)
        carry_before = carry
        a, carry = solver.act(np.asarray(obs), carry, eps, rng)
        next_frame, r, done, over = env.step(a)
        next_obs = stacker.push(next_frame) if pixel else next_frame
        ep_ret += r
        for seq in builder.on_step(obs, a, r, done,
                                   (carry_before[0][0], carry_before[1][0]),
                                   next_obs):
            replay.add_sequence(seq)
        obs = next_obs
        metrics.count("env_steps")

        if over:
            if not done:
                # time-limit truncation: emit the pending window with its
                # bootstrap intact instead of discarding the episode tail
                for seq in builder.flush_truncated(next_obs):
                    replay.add_sequence(seq)
            ep_returns.add(ep_ret)
            ep_ret = 0.0
            builder.reset()
            frame = env.reset()
            obs = stacker.reset(frame) if pixel else frame
            carry = solver.initial_state(1)

        if (replay.ready(learn_start_seqs)
                and t % cfg.train.train_every == 0):
            if fused_seq:
                remaining = ((cfg.train.total_steps - t)
                             // cfg.train.train_every + 1)
                m = stream.next(remaining)
            else:
                batch = replay.sample(cfg.replay.batch_size)
                sampled_at = batch.pop("_sampled_at")
                if device_seq:
                    m = solver.train_step_from_ring(replay, batch)
                else:
                    m = solver.train_step(batch)
            gsteps += 1
            if writeback is not None:
                writeback.push(m["index"], m["td_abs"], sampled_at)
            metrics.count("grad_steps")
            if ckpt and gsteps % cfg.train.checkpoint_every == 0:
                ckpt.save(solver.state, extra={"env_steps": t})
                if persist:
                    from distributed_deep_q_tpu.replay.persistence import (
                        save_replay)
                    save_replay(replay, persist)
            if gsteps % log_every == 0:
                summary = {
                    "loss": float(m["loss"]), "q_mean": float(m["q_mean"]),
                    "return_avg100": ep_returns.value, "epsilon": eps,
                    "grad_steps_per_s": metrics.rate("grad_steps"),
                    "env_steps_per_s": metrics.rate("env_steps"),
                }
                metrics.gauge("queue/replay_size", len(replay))
                pending = getattr(replay, "pending_rows", None)
                if pending is not None:
                    metrics.gauge("queue/staged_rows", pending())
                metrics.log(gsteps, **summary, **metrics.telemetry())

    if writeback:
        writeback.drain()
    if ckpt:
        ckpt.save(solver.state, extra={"env_steps": cfg.train.total_steps},
                  wait=True)
    if persist:
        # unconditional end-of-run save (mirrors train_single_process):
        # without it, persist without checkpointing is silently inert and
        # with checkpointing the buffer goes stale vs the final θ
        from distributed_deep_q_tpu.replay.persistence import save_replay
        save_replay(replay, persist)
    summary["final_return_avg100"] = ep_returns.value
    summary["eval_return"] = evaluate_recurrent(solver, cfg)
    summary["solver"] = solver
    summary["replay"] = replay
    return summary
