"""Chaos smoke — prove the RPC fault-tolerance stack end to end.

Eleven modes:

``python scripts/chaos_smoke.py [num_actors] [spec]`` (default)
    Threaded actor fleet over the production wire protocol: resilient
    clients stream LABELED transitions into a ``ReplayFeedServer`` while
    the chaos shim drops and truncates connections on both sides, and the
    learner is killed and warm-rebooted from its snapshot mid-run on the
    same port. Prints one JSON verdict line; exit status 1 if any
    transition was lost or duplicated. Fast (seconds), CPU-only, no jax —
    runnable on any box as a release gate for the resilience plane. The
    run is traced end to end (sample_rate=1), and the verdict also gates
    on causal integrity: zero orphan spans, retry cycles visible as
    ``retry`` instants (overload mode: sheds visible as ``shed``).

``python scripts/chaos_smoke.py overload [spec]``
    Overload acceptance (ISSUE 5): a producer fleet deliberately outruns a
    rate-capped consumer, so the server's admission controller must shed —
    the gate is *shed but never lost*: every transition lands exactly once
    (the shed flush re-stages under its original ``flush_seq``), sheds
    actually fired, and the clients' token buckets paced to the granted
    credits. Chaos delays compose on top via the optional spec.

``python scripts/chaos_smoke.py ingest [spec]``
    Ingest-saturation acceptance (ISSUE 8): a producer fleet streams
    LABELED pixel frames into a device replay ring through the full
    columnar path — wire decode → ``ColumnStage`` staged-append →
    ``IngestDrain`` batched flush — faster than a rate-capped consumer,
    so the admission controller must shed. The gate is the overload
    contract held at saturation through the NEW staging plane: sheds
    fired, the drain (not the writers) carried the flushes, and every
    frame landed in the HBM ring exactly once (ids decoded back out of
    the ring rows).

``python scripts/chaos_smoke.py inference [spec]``
    Inference-plane acceptance (ISSUE 9): a client fleet streams
    deterministic labeled observations at an ``InferenceServer`` while
    the chaos shim drops, truncates, delays, and bit-flips connections.
    Every reply's action is checked against the local argmax of the SAME
    θ for that exact observation — the gate is zero wrong, zero missing,
    zero duplicated actions despite reconnects and shed/retry cycles
    (``infer`` is pure in (θ, obs), so retries need no dedup; a wrong
    action would mean a slicing/padding/batching bug under fault load).

``python scripts/chaos_smoke.py vector [spec]``
    Vector-actor acceptance (ISSUE 11): the vectorized acting loop's
    ε-greedy tick (``select_actions`` over labeled observation batches)
    drives the production ``_RemoteInference`` retry path while the
    chaos shim drops/truncates the wire AND the inference server is
    hard-killed mid-run, then rebooted with the same θ on the same
    port. The gate: the loop rode out the outage through shed/retry
    with zero wrong, zero duplicated, and zero missing actions — every
    tick's action vector matches a local same-seed oracle replay of the
    identical ε-stream, so the greedy-subset batching (only non-explore
    rows ride the RPC) never crossed rows under fault load.

``python scripts/chaos_smoke.py health [spec]``
    Health-plane acceptance (ISSUE 13): clean traffic streams into a
    ``ReplayFeedServer`` whose ``health`` RPC a supervisor-side
    ``FleetHealth`` scrapes on every tick, with the SLO windows shrunk
    to fractions of a second. Mid-run, ``corrupt=`` wire chaos is
    installed: CRC-rejected frames move ``rpc/checksum_errors``, whose
    rate_above(0) burn-rate rule must flip the FLEET verdict ok →
    degraded with the finding naming ``wire_integrity``; after the
    chaos is uninstalled the hysteresis clear must bring it back to ok.
    The gate: the full ok → degraded → ok arc, ZERO critical flaps
    (every default rule is degraded-severity — a wire fault must never
    page as critical), and the per-tick ``health/verdict`` JSONL the
    run writes passes ``telemetry_report``'s strict SLO checks after
    recovery.

``python scripts/chaos_smoke.py learn [spike]``
    Learning-divergence acceptance (ISSUE 16): a synthetic learner
    feeds learning-dynamics planes (``learning.py`` layout) through the
    unmodified production read path — ``LearnAccumulator`` fold,
    ``learn/*`` gauges, divergence ``TrendRule``s, ``FleetHealth``. A
    mid-run lr spike (multiplicative loss/grad-norm growth per step)
    must flip the fleet verdict ok → degraded with ``loss_divergence``
    named; restoring the lr must walk it back to a STABLE ok. The gate:
    the full arc, zero critical flaps, schema-valid verdict JSONL, and
    ``telemetry_report``'s strict learn gate still catching the
    recovered divergence.

``python scripts/chaos_smoke.py durability [cycles] [spec]``
    Crash-recovery acceptance (ISSUE 6): the server is hard-killed at
    random points across the snapshot cadence over ≥ 20 cycles — before,
    during (async dump in flight), and after commits — under ``torn=``
    disk damage and ``corrupt=`` wire flips, with fabricated
    crashed-before-commit generation directories thrown in. The gate:
    every warm boot lands exactly on the newest generation that verifies
    clean (checked against an independent pre-boot probe), and after
    actors replay their full labeled history through the flush-seq dedup
    there are zero lost, zero duplicated, and zero corrupt rows.

``python scripts/chaos_smoke.py churn``
    Elastic-fleet acceptance (ISSUE 17): two learner hosts serve a
    hash-assigned actor fleet through the membership registry; mid-run
    one host gracefully retires (replay shard exported through the
    GenerationStore handoff) and a fresh host imports the shard and
    joins. The gate: the fleet verdict walks ok → degraded
    (``member_unreachable`` named) → ok with zero critical flaps, the
    autoscaler's shrink/grow decisions land lineage-traceable in the
    run JSONL, remapped actors reconnect (``rpc/mass_reconnects``
    moves) with in-flight flushes exactly-once across the handoff, and
    the labeled-frame ledger over the union of surviving shards shows
    zero lost, zero duplicated transitions and zero wrong actions.

``python scripts/chaos_smoke.py tenants``
    Closed-control-loop acceptance (ISSUE 20), two arcs on one JSONL.
    Arc 1 — multi-tenant serving: one ``InferenceServer`` serves a
    primary θ, an A/B arm, and a mirror-only shadow tenant to a
    hash-split client fleet under wire chaos while a forward-latency
    stall overloads the queue; the degrade ladder must shed strictly
    shadow → ab → primary, shadow replies must never reach a client,
    per-tenant SLO rules must name ``tenant/*`` findings, and every
    reply must carry the RIGHT arm's action and θ version (per-arm
    oracle replay: zero lost, duplicated, or wrong). Arc 2 — autoscale
    executor: a spawned actor fleet streams labeled transitions while a
    burst producer forces ``ingest_shed``; the health-driven autoscaler
    must shrink, the executor must drain + retire a REAL process
    (eviction of its exactly-once dedup stamp included, terminations
    counted separately from kill escalations), and the recovery streak
    must grow it back — with every applied action lineage-traceable to
    a named Decision and ``telemetry_report``'s strict SLO + elastic
    gates passing on the run JSONL.

``python scripts/chaos_smoke.py train [cfg.overrides ...]``
    The full distributed trainer (spawned actor processes, mesh learner)
    on CartPole with chaos enabled via ``cfg.actors.chaos`` — the env-var
    propagation path the fleet uses in production. Slower (jax import per
    spawned child); prints the run summary with the robustness counters
    (restarts, kill escalations, dispatch errors, duplicate flushes).

Thread actors in the default mode for the same reason as
``fleet_smoke.py``: the RPC boundary is what's under test, and labeled
payloads make loss/duplication decidable exactly.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _trace_begin():
    """Turn the tracer fully on for a chaos run (every span, no
    sampling): the run doubles as the causal-integrity acceptance —
    faults must not orphan spans or lose SHED/retry events."""
    from distributed_deep_q_tpu import tracing

    tracing.reset()
    tracing.configure(enabled=True, sample_rate=1.0, lineage_rate=1.0,
                      buffer_spans=1 << 17)
    return tracing


def _trace_verdict(tracing) -> dict:
    """Drain the traced run and check causal integrity. An orphan is an
    event whose ``parent`` span id was never recorded — under chaos that
    would mean a dropped/torn context, so the count gates ``ok``."""
    events = tracing.drain()
    dropped = tracing.drop_count()
    tracing.disable()
    ids = {e["args"]["span"] for e in events if e.get("ph") == "X"}
    ids.add(0)
    orphans = [e for e in events if e["args"].get("parent", 0) not in ids]
    instants: dict[str, int] = {}
    for e in events:
        if e.get("ph") == "i":
            instants[e["name"]] = instants.get(e["name"], 0) + 1
    return {"spans": sum(1 for e in events if e.get("ph") == "X"),
            "orphan_spans": len(orphans),
            "span_drops": dropped,
            "instants": instants}


def run_chaos_smoke(num_actors: int = 4, flushes: int = 120, rows: int = 8,
                    spec: str = "drop=0.03,truncate=0.02,seed=11",
                    deadline: float = 120.0) -> dict:
    from distributed_deep_q_tpu.replay.replay_memory import ReplayMemory
    from distributed_deep_q_tpu.rpc import faultinject
    from distributed_deep_q_tpu.rpc.replay_server import ReplayFeedServer
    from distributed_deep_q_tpu.rpc.resilience import (
        ResilientReplayFeedClient, RetryPolicy)

    trc = _trace_begin()
    plan = faultinject.install(spec)
    snap = tempfile.mktemp(prefix="chaos_smoke_")
    total = num_actors * flushes * rows
    replay = ReplayMemory(max(2 * total, 1024), (2,), np.float32, seed=0)
    server = ReplayFeedServer(replay)
    host, port = server.address
    policy = RetryPolicy(base_delay=0.01, max_delay=0.2, deadline=deadline)
    errors: list[str] = []
    retries = [0] * num_actors

    def actor(aid: int) -> None:
        try:
            c = ResilientReplayFeedClient.connect(
                host, port, actor_id=aid, policy=policy, seed=100 + aid)
            for f in range(flushes):
                ids = aid * 1_000_000 + f * 1_000 + np.arange(
                    rows, dtype=np.float32)
                obs = np.stack([ids, ids], axis=1)
                c.add_transitions(
                    obs=obs, action=np.zeros(rows, np.int32),
                    reward=np.zeros(rows, np.float32), next_obs=obs,
                    discount=np.ones(rows, np.float32))
                time.sleep(0.001)
            retries[aid] = c.retries
            c.close()
        except Exception as e:  # noqa: BLE001 — reported in the verdict
            errors.append(f"actor {aid}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=actor, args=(a,), daemon=True)
               for a in range(num_actors)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()

    # kill + warm-reboot the learner once about half the traffic landed
    t_end = time.monotonic() + deadline / 2
    while server.counters()["env_steps"] < total // 2 \
            and time.monotonic() < t_end:
        time.sleep(0.01)
    server.shutdown(snap)
    replay2 = ReplayMemory(max(2 * total, 1024), (2,), np.float32, seed=0)
    server = ReplayFeedServer(replay2, host=host, port=port,
                              snapshot_path=snap)

    for t in threads:
        t.join(timeout=deadline)
    hung = sum(t.is_alive() for t in threads)
    wall = time.perf_counter() - t0
    rpc = server.telemetry.robustness_counters()

    expected = {a * 1_000_000 + f * 1_000 + r for a in range(num_actors)
                for f in range(flushes) for r in range(rows)}
    observed = replay2.obs[:len(replay2), 0].astype(np.int64).tolist()
    lost = len(expected) - len(set(observed))
    duplicated = len(observed) - len(set(observed))
    verdict = {
        "ok": not errors and not hung and lost == 0 and duplicated == 0,
        "num_actors": num_actors,
        "transitions_sent": total,
        "transitions_stored": len(observed),
        "lost": lost,
        "duplicated": duplicated,
        "chaos_spec": spec,
        "faults_fired": dict(sorted(plan.counters.items())),
        "client_retries": sum(retries),
        "duplicate_flushes_absorbed": rpc["duplicate_flushes"],
        "dispatch_errors": rpc["dispatch_errors"],
        "hung_actors": hung,
        "errors": errors,
        "wall_s": round(wall, 2),
    }
    server.close()
    faultinject.uninstall()
    trace = _trace_verdict(trc)
    verdict["trace"] = trace
    # causal integrity under drop/truncate chaos: no orphaned spans, and
    # every client retry cycle left a visible "retry" instant
    verdict["ok"] = (verdict["ok"] and trace["orphan_spans"] == 0
                     and (sum(retries) == 0
                          or trace["instants"].get("retry", 0) > 0))
    return verdict


def run_overload_smoke(num_actors: int = 3, flushes: int = 40, rows: int = 16,
                       spec: str = "delay=0.05:20,seed=13",
                       consume_rate: float = 300.0,
                       deadline: float = 120.0) -> dict:
    """Producer fleet ~10× faster than a rate-capped consumer: the server
    MUST shed, and the gate is shed-but-never-lost — exactly-once delivery
    of every labeled transition despite admission control plus chaos."""
    from distributed_deep_q_tpu.replay.replay_memory import ReplayMemory
    from distributed_deep_q_tpu.rpc import faultinject
    from distributed_deep_q_tpu.rpc.flowcontrol import FlowConfig
    from distributed_deep_q_tpu.rpc.replay_server import ReplayFeedServer
    from distributed_deep_q_tpu.rpc.resilience import (
        ResilientReplayFeedClient, RetryPolicy)

    trc = _trace_begin()
    plan = faultinject.install(spec) if spec else None
    total = num_actors * flushes * rows
    replay = ReplayMemory(max(2 * total, 1024), (2,), np.float32, seed=0)
    # tight ingest_factor so the mismatch branch trips as soon as the
    # consumer's rate is observable; floor small enough to actually pace
    flow = FlowConfig(ingest_factor=1.5, flush_credit_floor=8,
                      rate_halflife_s=0.5)
    server = ReplayFeedServer(replay, flow=flow)
    host, port = server.address
    policy = RetryPolicy(base_delay=0.01, max_delay=0.2, deadline=deadline)
    errors: list[str] = []
    stop = threading.Event()
    clients: list = [None] * num_actors

    def consumer() -> None:
        # rate-capped learner stand-in: sample under the server's lock,
        # feed the flow controller's consumption EWMA
        batch = 32
        while not stop.is_set():
            with server.replay_lock:
                ready = len(replay) >= batch
                if ready:
                    replay.sample(batch)
            if ready:
                server.note_consumed(batch)
                time.sleep(batch / consume_rate)
            else:
                time.sleep(0.005)

    def actor(aid: int) -> None:
        try:
            c = ResilientReplayFeedClient.connect(
                host, port, actor_id=aid, policy=policy, seed=200 + aid)
            clients[aid] = c
            for f in range(flushes):  # no pacing: outrun the consumer
                ids = aid * 1_000_000 + f * 1_000 + np.arange(
                    rows, dtype=np.float32)
                obs = np.stack([ids, ids], axis=1)
                c.add_transitions(
                    obs=obs, action=np.zeros(rows, np.int32),
                    reward=np.zeros(rows, np.float32), next_obs=obs,
                    discount=np.ones(rows, np.float32))
            c.close()
        except Exception as e:  # noqa: BLE001 — reported in the verdict
            errors.append(f"actor {aid}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=actor, args=(a,), daemon=True)
               for a in range(num_actors)]
    drain = threading.Thread(target=consumer, daemon=True)
    t0 = time.perf_counter()
    drain.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=deadline)
    hung = sum(t.is_alive() for t in threads)
    stop.set()
    drain.join(timeout=5)
    wall = time.perf_counter() - t0

    rpc = server.telemetry.robustness_counters()
    fc = server.flow_counters()
    expected = {a * 1_000_000 + f * 1_000 + r for a in range(num_actors)
                for f in range(flushes) for r in range(rows)}
    observed = replay.obs[:len(replay), 0].astype(np.int64).tolist()
    lost = len(expected) - len(set(observed))
    duplicated = len(observed) - len(set(observed))
    client_sheds = sum(c.sheds for c in clients if c is not None)
    throttled = sum(c.throttled_s for c in clients if c is not None)
    verdict = {
        # the acceptance: overload produced sheds AND nothing was lost or
        # duplicated — backpressure is explicit cooperation, not data loss
        "ok": (not errors and not hung and lost == 0 and duplicated == 0
               and rpc["shed_flushes"] > 0),
        "num_actors": num_actors,
        "transitions_sent": total,
        "transitions_stored": len(observed),
        "lost": lost,
        "duplicated": duplicated,
        "shed_flushes": rpc["shed_flushes"],
        "client_sheds": client_sheds,
        "client_throttled_s": round(throttled, 3),
        "duplicate_flushes_absorbed": rpc["duplicate_flushes"],
        "degraded_trips": fc["degraded_trips"],
        "consume_rate_cap": consume_rate,
        "chaos_spec": spec,
        "faults_fired": dict(sorted(plan.counters.items())) if plan else {},
        "hung_actors": hung,
        "errors": errors,
        "wall_s": round(wall, 2),
    }
    server.close()
    faultinject.uninstall()
    trace = _trace_verdict(trc)
    verdict["trace"] = trace
    # sheds are cooperation, not loss — and they must be VISIBLE: every
    # client shed/re-stage cycle leaves a distinct "shed" instant
    verdict["ok"] = (verdict["ok"] and trace["orphan_spans"] == 0
                     and (client_sheds == 0
                          or trace["instants"].get("shed", 0) > 0))
    return verdict


def run_ingest_saturation_smoke(num_actors: int = 3, flushes: int = 40,
                                rows: int = 16,
                                spec: str = "delay=0.05:20,seed=17",
                                consume_rate: float = 300.0,
                                deadline: float = 120.0) -> dict:
    """Overload contract at saturation through the columnar ingest path.

    Same shed-but-never-lost acceptance as ``overload``, but the replay
    is a DEVICE ring fed through the full ISSUE 8 plane: frame batches
    decode off the wire, staged-append into per-shard ``ColumnStage``
    buffers under the replay lock, and the ``IngestDrain`` thread (which
    the server attaches at boot) batches the H2D flushes. Every frame
    carries its id in its first four pixel bytes, so after shutdown the
    HBM ring itself answers lost/duplicated exactly — a dedup slip or a
    drain/staging race would surface as a wrong multiset of ids."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from distributed_deep_q_tpu.config import MeshConfig, ReplayConfig
    from distributed_deep_q_tpu.parallel.mesh import make_mesh
    from distributed_deep_q_tpu.replay.device_ring import DeviceFrameReplay
    from distributed_deep_q_tpu.rpc import faultinject
    from distributed_deep_q_tpu.rpc.flowcontrol import FlowConfig
    from distributed_deep_q_tpu.rpc.replay_server import ReplayFeedServer
    from distributed_deep_q_tpu.rpc.resilience import (
        ResilientReplayFeedClient, RetryPolicy)

    trc = _trace_begin()
    plan = faultinject.install(spec) if spec else None
    total = num_actors * flushes * rows
    mesh = make_mesh(MeshConfig(backend="cpu", num_fake_devices=8, dp=1))
    # capacity sized so no slot wraps (exactly-once stays decidable from
    # final ring contents); one stream slot per actor
    cfg = ReplayConfig(capacity=6144, batch_size=32, prioritized=False)
    replay = DeviceFrameReplay(cfg, mesh, (8, 8), stack=4, gamma=0.99,
                               seed=0, write_chunk=64,
                               num_streams=num_actors)
    flow = FlowConfig(ingest_factor=1.5, flush_credit_floor=8,
                      rate_halflife_s=0.5)
    server = ReplayFeedServer(replay, flow=flow)
    host, port = server.address
    policy = RetryPolicy(base_delay=0.01, max_delay=0.2, deadline=deadline)
    errors: list[str] = []
    stop = threading.Event()
    clients: list = [None] * num_actors

    def consumer() -> None:
        # rate-capped learner stand-in: only the consumption EWMA matters
        # here (device sampling is exercised elsewhere)
        batch = 32
        while not stop.is_set():
            server.note_consumed(batch)
            time.sleep(batch / consume_rate)

    def frame_ids(aid: int, f: int) -> np.ndarray:
        # non-zero ids (unwritten ring rows read back as zeros)
        return ((aid + 1) * 1_000_000 + f * 1_000
                + np.arange(rows, dtype=np.uint32))

    def actor(aid: int) -> None:
        try:
            c = ResilientReplayFeedClient.connect(
                host, port, actor_id=aid, policy=policy, seed=300 + aid)
            clients[aid] = c
            for f in range(flushes):  # no pacing: outrun the consumer
                frames = np.zeros((rows, 8, 8), np.uint8)
                frames.reshape(rows, 64)[:, :4] = \
                    frame_ids(aid, f).view(np.uint8).reshape(rows, 4)
                c.add_transitions(
                    frame=frames, action=np.zeros(rows, np.int32),
                    reward=np.zeros(rows, np.float32),
                    done=np.zeros(rows, bool),
                    boundary=np.zeros(rows, bool))
            c.close()
        except Exception as e:  # noqa: BLE001 — reported in the verdict
            errors.append(f"actor {aid}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=actor, args=(a,), daemon=True)
               for a in range(num_actors)]
    pacer = threading.Thread(target=consumer, daemon=True)
    t0 = time.perf_counter()
    pacer.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=deadline)
    hung = sum(t.is_alive() for t in threads)
    stop.set()
    pacer.join(timeout=5)
    wall = time.perf_counter() - t0

    rpc = server.telemetry.robustness_counters()
    drained = server.telemetry_summary()
    server.close()  # stops the drain; its shutdown flush lands stragglers
    if plan:
        faultinject.uninstall()

    expected = {int(i) for a in range(num_actors) for f in range(flushes)
                for i in frame_ids(a, f)}
    ring = np.asarray(replay.ring)  # [capacity, 64] uint8
    ids = np.ascontiguousarray(ring[:, :4]).view(np.uint32).ravel()
    observed = ids[ids > 0].astype(np.int64).tolist()
    lost = len(expected - set(observed))
    duplicated = len(observed) - len(set(observed))
    corrupt = len(set(observed) - expected)
    client_sheds = sum(c.sheds for c in clients if c is not None)
    verdict = {
        # the acceptance: saturation produced sheds, the drain thread
        # carried the flushes, and the ring holds every id exactly once
        "ok": (not errors and not hung and lost == 0 and duplicated == 0
               and corrupt == 0 and rpc["shed_flushes"] > 0
               and drained.get("ingest/drain_flushes", 0) > 0
               and replay.pending_rows() == 0),
        "num_actors": num_actors,
        "transitions_sent": total,
        "transitions_stored": len(observed),
        "lost": lost,
        "duplicated": duplicated,
        "corrupt_rows": corrupt,
        "shed_flushes": rpc["shed_flushes"],
        "client_sheds": client_sheds,
        "drained_rows": drained.get("ingest/drained_rows", 0),
        "drain_flushes": drained.get("ingest/drain_flushes", 0),
        "rows_left_staged": replay.pending_rows(),
        "duplicate_flushes_absorbed": rpc["duplicate_flushes"],
        "consume_rate_cap": consume_rate,
        "chaos_spec": spec,
        "faults_fired": dict(sorted(plan.counters.items())) if plan else {},
        "hung_actors": hung,
        "errors": errors,
        "wall_s": round(wall, 2),
    }
    trace = _trace_verdict(trc)
    verdict["trace"] = trace
    verdict["ok"] = (verdict["ok"] and trace["orphan_spans"] == 0
                     and (client_sheds == 0
                          or trace["instants"].get("shed", 0) > 0))
    return verdict


def run_inference_chaos_smoke(
        num_clients: int = 4, requests: int = 100,
        spec: str = "drop=0.03,truncate=0.02,corrupt=0.01,seed=29",
        deadline: float = 120.0) -> dict:
    """Remote-inference fleet under wire chaos: every action must be
    RIGHT, not just delivered.

    Each client sends labeled single-row observations (deterministic in
    ``(client, i)``) through the resilient retry idiom — reconnect on
    transport failure, back off on shed — and records the action the
    server returned. The oracle is a second ``BatchedPolicy`` built from
    the same seed with bucket (1,): the canonical per-actor CPU forward
    the remote plane replaces. Zero mismatches proves the microbatcher's
    pad/slice/concat machinery never crossed wires between concurrent
    clients, even while chaos forced partial batches and re-sends."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from distributed_deep_q_tpu.config import InferenceConfig, NetConfig
    from distributed_deep_q_tpu.models.policy import BatchedPolicy
    from distributed_deep_q_tpu.rpc import faultinject
    from distributed_deep_q_tpu.rpc.flowcontrol import FlowConfig
    from distributed_deep_q_tpu.rpc.inference_server import (
        InferenceClient, InferenceServer)

    trc = _trace_begin()
    plan = faultinject.install(spec) if spec else None
    obs_dim = 8
    icfg = InferenceConfig()
    net = NetConfig(kind="mlp", hidden=(32, 32), num_actions=4)
    policy = BatchedPolicy(net, seed=7, obs_dim=obs_dim,
                           buckets=icfg.buckets)
    server = InferenceServer(policy, max_batch=icfg.max_batch,
                             cutoff_us=icfg.cutoff_us,
                             flow=FlowConfig(flush_credit_floor=8))
    host, port = server.address

    def make_obs(aid: int, i: int) -> np.ndarray:
        # labeled: the observation IS the identity — a unique
        # deterministic vector per (client, request)
        r = np.random.default_rng(1_000 * (aid + 1) + i)
        return r.standard_normal(obs_dim).astype(np.float32)

    errors: list[str] = []
    sheds = [0] * num_clients
    got: list[dict[int, int]] = [{} for _ in range(num_clients)]

    def client(aid: int) -> None:
        c = None
        try:
            for i in range(requests):
                obs = make_obs(aid, i)[None]
                for _ in range(400):
                    try:
                        if c is None:
                            c = InferenceClient(host, port, actor_id=aid,
                                                timeout=5.0)
                        resp = c.call("infer", obs=obs, seq=i)
                    except Exception:  # noqa: BLE001 — chaos; reconnect
                        try:
                            if c is not None:
                                c.close()
                        except Exception:  # noqa: BLE001
                            pass
                        c = None
                        time.sleep(0.005)
                        continue
                    if resp.get("error"):
                        time.sleep(0.005)
                        continue
                    if resp.get("shed"):
                        sheds[aid] += 1
                        trc.instant("shed", plane="inference")
                        time.sleep(
                            max(resp.get("retry_after_ms", 10), 1) / 1e3)
                        continue
                    # infer is idempotent in (θ, obs): a retried request
                    # may land twice server-side, but the client keeps
                    # exactly one action per i — overwrite would only
                    # matter if replies disagreed, which mismatch catches
                    if i in got[aid]:
                        errors.append(f"client {aid}: duplicate reply "
                                      f"recorded for request {i}")
                    got[aid][i] = int(np.asarray(resp["actions"])[0])
                    break
                else:
                    errors.append(
                        f"client {aid}: request {i} never landed")
                    return
        except Exception as e:  # noqa: BLE001 — reported in the verdict
            errors.append(f"client {aid}: {type(e).__name__}: {e}")
        finally:
            try:
                if c is not None:
                    c.close()
            except Exception:  # noqa: BLE001
                pass

    threads = [threading.Thread(target=client, args=(a,), daemon=True)
               for a in range(num_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=deadline)
    hung = sum(t.is_alive() for t in threads)
    wall = time.perf_counter() - t0
    tm = server.telemetry_summary()
    server.close()
    if plan:
        faultinject.uninstall()

    # oracle AFTER the run so its forwards never interleave with the
    # server's batcher on the same jit cache mid-chaos
    oracle = BatchedPolicy(net, seed=7, obs_dim=obs_dim, buckets=(1,))
    wrong = missing = 0
    for aid in range(num_clients):
        for i in range(requests):
            if i not in got[aid]:
                missing += 1
                continue
            want, _ = oracle.forward(make_obs(aid, i)[None])
            if got[aid][i] != int(want[0]):
                wrong += 1
    total_sheds = sum(sheds)
    verdict = {
        "ok": (not errors and not hung and wrong == 0 and missing == 0),
        "num_clients": num_clients,
        "requests_sent": num_clients * requests,
        "replies": sum(len(g) for g in got),
        "wrong_actions": wrong,
        "missing_actions": missing,
        "client_sheds": total_sheds,
        "server_requests": tm.get("inference/requests", 0),
        "server_sheds": tm.get("inference/sheds", 0),
        "server_wire_errors": tm.get("inference/wire_errors", 0),
        "compiled_buckets": tm.get("inference/compiled_buckets", 0),
        "chaos_spec": spec,
        "faults_fired": dict(sorted(plan.counters.items())) if plan else {},
        "hung_clients": hung,
        "errors": errors,
        "wall_s": round(wall, 2),
    }
    trace = _trace_verdict(trc)
    verdict["trace"] = trace
    # shed/retry cycles must be VISIBLE as instants, and faults must not
    # orphan the infer_wait/infer_batch/infer_forward span tree
    verdict["ok"] = (verdict["ok"] and trace["orphan_spans"] == 0
                     and (total_sheds == 0
                          or trace["instants"].get("shed", 0) > 0))
    return verdict


def run_vector_chaos_smoke(
        num_envs: int = 8, ticks: int = 60,
        spec: str = "drop=0.02,truncate=0.01,seed=31",
        deadline: float = 120.0) -> dict:
    """Vectorized actor vs a dying inference server (ISSUE 11).

    One vector acting loop — the production ``select_actions`` ε-split
    over the production ``_RemoteInference`` stub — ticks labeled
    observation batches (deterministic in ``(tick, row)``) while wire
    chaos drops/truncates connections and, at the half-way tick, the
    ``InferenceServer`` is hard-killed and then rebooted with the SAME
    seed θ on the SAME port. Because ``infer`` is pure in (θ, obs) and θ
    survives the reboot, every action has exactly one right answer, so
    the oracle is a same-seed local replay: fresh rngs with the run's
    seeds re-consume the identical ε-stream against a bucket-(1,)
    ``BatchedPolicy``, and any divergence — a crossed row in the greedy
    subset, a stale retry landing on the wrong tick, an ε draw consumed
    twice — shows up as a wrong action, exactly.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")

    from distributed_deep_q_tpu.actors.supervisor import (
        _RemoteInference, actor_epsilon)
    from distributed_deep_q_tpu.actors.vector import select_actions
    from distributed_deep_q_tpu.config import Config, NetConfig
    from distributed_deep_q_tpu.models.policy import BatchedPolicy
    from distributed_deep_q_tpu.rpc import faultinject
    from distributed_deep_q_tpu.rpc.inference_server import InferenceServer

    trc = _trace_begin()
    plan = faultinject.install(spec) if spec else None
    hw, stack, n_act = (10, 10), 2, 4
    net = NetConfig(kind="mlp", hidden=(32, 32), num_actions=n_act,
                    frame_shape=hw, stack=stack)
    obs_dim = hw[0] * hw[1] * stack
    cfg = Config()
    cfg.net = net
    cfg.inference.enabled = True
    # tight backoff so the mid-run outage is ridden out in milliseconds,
    # not the production half-second ladder
    cfg.actors.rpc_retry_base = 0.01
    cfg.actors.rpc_retry_max = 0.2
    cfg.actors.rpc_retry_deadline = deadline

    def build_server():
        # SAME seed every boot: θ is identical across the kill, which is
        # what makes "wrong action" decidable through the reboot
        pol = BatchedPolicy(net, seed=7, obs_dim=obs_dim,
                            buckets=cfg.inference.buckets)
        return InferenceServer(pol, host=cfg.inference.host,
                               port=cfg.inference.port,
                               max_batch=cfg.inference.max_batch,
                               cutoff_us=cfg.inference.cutoff_us)

    server = build_server()
    cfg.inference.host, cfg.inference.port = server.address
    stop = threading.Event()
    remote = _RemoteInference(cfg, stop, actor_id=0, gid=0)

    def make_obs(t: int) -> np.ndarray:
        # labeled: the batch IS its identity — one deterministic uint8
        # frame stack per (tick, row), the vector loop's exact obs shape
        rows = [np.random.default_rng(1_000 * (t + 1) + j)
                .integers(0, 256, hw + (stack,)).astype(np.uint8)
                for j in range(num_envs)]
        return np.stack(rows)

    def make_rngs():
        return [np.random.default_rng(7777 * (j + 1))
                for j in range(num_envs)]

    epsilons = [actor_epsilon(j, num_envs, 0.4, 7.0)
                for j in range(num_envs)]
    got: dict[int, np.ndarray] = {}
    errors: list[str] = []
    duplicated = [0]
    progress = [0]

    def loop() -> None:
        rngs = make_rngs()
        try:
            for t in range(ticks):
                acts = select_actions(make_obs(t), rngs, epsilons, n_act,
                                      remote.actions)
                if t in got:
                    duplicated[0] += 1
                got[t] = acts
                progress[0] = t + 1
        except Exception as e:  # noqa: BLE001 — reported in the verdict
            errors.append(f"vector loop: {type(e).__name__}: {e}")

    th = threading.Thread(target=loop, daemon=True)
    t0 = time.perf_counter()
    th.start()
    # hard-kill the inference plane mid-run; the loop must shed/retry
    # through the outage, never skip or re-order a tick
    t_end = time.monotonic() + deadline / 2
    while progress[0] < ticks // 2 and time.monotonic() < t_end:
        time.sleep(0.005)
    kill_tick = progress[0]
    server.close()
    time.sleep(0.2)  # let in-flight calls hit the dead port
    server = build_server()  # same seed, same host:port — warm reboot
    th.join(timeout=deadline)
    hung = int(th.is_alive())
    stop.set()
    wall = time.perf_counter() - t0
    tm = server.telemetry_summary()
    remote.close()
    server.close()
    if plan:
        faultinject.uninstall()

    # oracle AFTER the run: replay the identical ε-stream against the
    # canonical bucket-(1,) local forward and demand bitwise agreement
    oracle = BatchedPolicy(net, seed=7, obs_dim=obs_dim, buckets=(1,))
    orngs = make_rngs()
    wrong = missing = 0
    for t in range(ticks):
        want = select_actions(make_obs(t), orngs, epsilons, n_act,
                              lambda rows: oracle.forward(rows)[0])
        if t not in got:
            missing += num_envs
            continue
        wrong += int(np.sum(got[t] != want))
    trace = _trace_verdict(trc)
    # the outage must be VISIBLE in the causal record: the resilient
    # stub's retry cycles and/or reconnects, plus any shed instants
    retry_events = (trace["instants"].get("retry", 0)
                    + trace["instants"].get("reconnect", 0))
    verdict = {
        "ok": (not errors and not hung and wrong == 0 and missing == 0
               and duplicated[0] == 0 and retry_events > 0
               and trace["orphan_spans"] == 0
               and (remote.sheds == 0
                    or trace["instants"].get("shed", 0) > 0)),
        "num_envs": num_envs,
        "ticks": ticks,
        "actions_checked": ticks * num_envs,
        "wrong_actions": wrong,
        "missing_actions": missing,
        "duplicated_ticks": duplicated[0],
        "kill_tick": kill_tick,
        "client_sheds": remote.sheds,
        "retry_events": retry_events,
        "reboot_server_requests": tm.get("inference/requests", 0),
        "chaos_spec": spec,
        "faults_fired": dict(sorted(plan.counters.items())) if plan else {},
        "hung": hung,
        "errors": errors,
        "wall_s": round(wall, 2),
        "trace": trace,
    }
    return verdict


def run_health_smoke(spec: str = "corrupt=0.35,seed=41",
                     deadline: float = 45.0) -> dict:
    """Injected wire fault drives the fleet verdict ok → degraded → ok.

    Every flush and every fleet scrape opens a FRESH connection — the
    chaos shim wraps sockets at connect time, so installing/uninstalling
    the plan at phase boundaries takes effect within one tick. The SLO
    windows are shrunk to fractions of a second (production keeps
    minutes); the burn-rate math is identical."""
    from distributed_deep_q_tpu import health
    from distributed_deep_q_tpu.metrics import Metrics
    from distributed_deep_q_tpu.replay.replay_memory import ReplayMemory
    from distributed_deep_q_tpu.rpc import faultinject
    from distributed_deep_q_tpu.rpc.replay_server import (
        ReplayFeedClient, ReplayFeedServer)

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts"))
    from telemetry_report import load_records, slo_problems

    health.configure(enabled=True, fast_window_s=0.5, slow_window_s=1.5,
                     clear_ratio=0.5)
    jsonl = tempfile.mktemp(prefix="health_smoke_", suffix=".jsonl")
    metrics = Metrics(jsonl_path=jsonl)
    replay = ReplayMemory(1 << 16, (2,), np.float32, seed=0)
    server = ReplayFeedServer(replay)
    host, port = server.address
    fleet = health.FleetHealth()

    def scrape_rpc() -> dict:
        c = ReplayFeedClient(host, port, actor_id=99, timeout=5.0)
        try:
            return c.health()
        finally:
            c.close()

    fleet.register("replay", scrape_rpc)

    seq = [0]

    def push_one() -> None:
        # stimulus traffic; under corrupt chaos a flush may need several
        # tries (CRC reject → error reply) or never land — both fine,
        # the traffic only exists to exercise the wire
        rows = 8
        ids = seq[0] * 1_000 + np.arange(rows, dtype=np.float32)
        obs = np.stack([ids, ids], axis=1)
        for _ in range(20):
            c = None
            try:
                c = ReplayFeedClient(host, port, actor_id=0, timeout=5.0)
                resp = c.call(
                    "add_transitions", flush_seq=seq[0], obs=obs,
                    next_obs=obs, action=np.zeros(rows, np.int32),
                    reward=np.zeros(rows, np.float32),
                    discount=np.ones(rows, np.float32))
            except Exception:  # noqa: BLE001 — chaos; retry fresh
                time.sleep(0.002)
                continue
            finally:
                if c is not None:
                    try:
                        c.close()
                    except Exception:  # noqa: BLE001
                        pass
            if resp.get("error") or resp.get("shed"):
                time.sleep(0.002)
                continue
            seq[0] += 1
            return

    step = [0]
    statuses: list[str] = []
    critical_flaps = [0]
    rules_fired: set[str] = set()

    def tick(collect_rules: bool = False) -> None:
        push_one()
        v = fleet.scrape()
        statuses.append(v.status)
        if v.status == "critical":
            critical_flaps[0] += 1
        if collect_rules and v.status != "ok":
            rules_fired.update(f.rule for f in v.findings)
        metrics.log(step[0], **{**fleet.gauges(),
                                "health/verdict": v.to_jsonable()})
        step[0] += 1
        time.sleep(0.03)

    def run_until(pred, min_s: float = 0.0, max_s: float = 15.0,
                  collect_rules: bool = False) -> bool:
        t0 = time.monotonic()
        while True:
            tick(collect_rules)
            elapsed = time.monotonic() - t0
            if elapsed >= min_s and pred():
                return True
            if elapsed > max_s:
                return False

    t0 = time.perf_counter()
    max_s = deadline / 3
    # phase A: clean traffic must settle on ok with warmed rings
    phase_a_ok = run_until(lambda: statuses[-1] == "ok",
                           min_s=1.0, max_s=max_s)
    # phase B: corrupt wire — CRC rejects burn wire_integrity's budget.
    # A failed scrape already degrades the verdict (member_unreachable),
    # so the phase gate demands the burn-rate rule ITSELF: degraded with
    # wire_integrity named in the findings
    plan = faultinject.install(spec)
    degraded_reached = run_until(
        lambda: statuses[-1] == "degraded"
        and "wire_integrity" in rules_fired,
        max_s=max_s, collect_rules=True)
    # phase C: recovery — the fast window cools, hysteresis clears
    faultinject.uninstall()
    recovered = run_until(
        lambda: len(statuses) >= 3 and statuses[-3:] == ["ok"] * 3,
        min_s=0.5, max_s=max_s)
    wall = time.perf_counter() - t0

    checksum_errors = \
        server.telemetry.robustness_counters()["checksum_errors"]
    metrics.close()
    server.close()
    health.reset()

    # the run JSONL must carry schema-valid aggregated verdicts and pass
    # the report's strict SLO checks now that the run ended ok
    records = load_records(jsonl)
    verdicts = [r["health/verdict"] for r in records
                if isinstance(r.get("health/verdict"), dict)]
    schema_ok = bool(verdicts) and all(
        v.get("status") in ("ok", "degraded", "critical")
        and isinstance(v.get("ok"), bool)
        and isinstance(v.get("findings"), list)
        and all(isinstance(f, dict) and "rule" in f and "key" in f
                and "severity" in f for f in v["findings"])
        for v in verdicts)
    slo = slo_problems(records)

    verdict = {
        "ok": (phase_a_ok and degraded_reached and recovered
               and critical_flaps[0] == 0
               and "wire_integrity" in rules_fired
               and schema_ok and not slo),
        "phase_a_ok": phase_a_ok,
        "degraded_reached": degraded_reached,
        "recovered": recovered,
        "critical_flaps": critical_flaps[0],
        "rules_fired": sorted(rules_fired),
        "wire_checksum_rejections": checksum_errors,
        "faults_fired": dict(sorted(plan.counters.items())),
        "scrapes": step[0],
        "jsonl_records": len(records),
        "verdicts_logged": len(verdicts),
        "verdict_schema_ok": schema_ok,
        "slo_problems": slo,
        "chaos_spec": spec,
        "wall_s": round(wall, 2),
    }
    return verdict


def run_learn_divergence_smoke(spike: float = 3.0,
                               deadline: float = 45.0) -> dict:
    """Simulated lr spike drives the learner verdict ok → degraded
    (``loss_divergence`` named) → ok, with hysteresis and no
    false-critical flaps.

    The learning-dynamics plane is synthesized host-side in exactly the
    layout the device returns (``learning.py``; TD counts bucketed by a
    real ``metrics.Histogram`` so the geometry twin is exercised, not
    re-derived): a stable learner, then a mid-run lr spike modeled as
    multiplicative loss/grad-norm growth per grad step — the signature
    of a step size past the stability edge — then recovery. The full
    production read path runs unmodified: ``LearnAccumulator`` fold →
    ``learn/*`` gauges → ``HealthMonitor`` divergence trends →
    ``FleetHealth`` aggregation → JSONL verdicts → the telemetry
    report's strict learn gate. Windows are shrunk to fractions of a
    second (production keeps minutes); the trend math is identical."""
    from distributed_deep_q_tpu import health, learning
    from distributed_deep_q_tpu.metrics import Histogram, Metrics

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts"))
    from telemetry_report import (
        learn_problems, load_records, slo_problems)

    health.configure(enabled=True, fast_window_s=0.5, slow_window_s=1.5,
                     clear_ratio=0.5)
    jsonl = tempfile.mktemp(prefix="learn_smoke_", suffix=".jsonl")
    metrics = Metrics(jsonl_path=jsonl)
    acc = learning.LearnAccumulator()
    monitor = health.HealthMonitor(rules=health.default_learn_rules(),
                                   trends=health.default_learn_trends(),
                                   name="learner")
    fleet = health.FleetHealth()
    fleet.register("learner", learning.learn_scrape_fn(acc, monitor))

    rng = np.random.default_rng(7)
    state = {"loss": 1.0, "gnorm": 2.0}

    def synth_plane() -> np.ndarray:
        td = rng.lognormal(mean=0.0, sigma=0.5, size=64)
        w = rng.uniform(0.3, 1.0, 64)
        prio = (td + 1e-6) ** 0.6
        h = Histogram(learning.TD_LO, learning.TD_HI,
                      learning.TD_PER_DECADE)
        h.observe_many(td)
        p = np.zeros(learning.PLANE_SIZE)
        p[:learning.N_HIST] = h._counts
        p[learning.I_TD_SUM] = td.sum()
        p[learning.I_PRIO_SUM] = prio.sum()
        p[learning.I_ISW_SUM] = w.sum()
        p[learning.I_SAMPLES] = td.size
        p[learning.I_LOSS_SUM] = state["loss"]
        p[learning.I_GNORM_SUM] = state["gnorm"]
        p[learning.I_GNORM_CLIP_SUM] = min(state["gnorm"], 10.0)
        p[learning.I_QMEAN_SUM] = 0.5
        p[learning.I_STEPS] = 1.0
        p[learning.I_TD_MAX] = td.max()
        p[learning.I_Q_MAX] = 1.0
        p[learning.I_PRIO_MAX] = prio.max()
        p[learning.I_ISW_MIN] = w.min()
        p[learning.I_TD_MIN] = td.min()
        return p

    step = [0]
    statuses: list[str] = []
    critical_flaps = [0]
    rules_fired: set[str] = set()

    def tick(collect_rules: bool = False) -> None:
        acc.ingest(synth_plane())
        v = fleet.scrape()
        statuses.append(v.status)
        if v.status == "critical":
            critical_flaps[0] += 1
        if collect_rules and v.status != "ok":
            rules_fired.update(f.rule for f in v.findings)
        metrics.log(step[0], **{**fleet.gauges(), **acc.gauges(),
                                "health/verdict": v.to_jsonable()})
        step[0] += 1
        time.sleep(0.03)

    def run_until(pred, min_s: float = 0.0, max_s: float = 15.0,
                  collect_rules: bool = False, pre=None) -> bool:
        t0 = time.monotonic()
        while True:
            if pre is not None:
                pre()
            tick(collect_rules)
            elapsed = time.monotonic() - t0
            if elapsed >= min_s and pred():
                return True
            if elapsed > max_s:
                return False

    t0 = time.perf_counter()
    max_s = deadline / 3
    # phase A: a healthy learner must settle on ok with warmed rings
    phase_a_ok = run_until(lambda: statuses[-1] == "ok",
                           min_s=1.0, max_s=max_s)

    # phase B: the lr spike — loss and grad norm grow multiplicatively
    # per grad step. The phase gate demands the drift rule ITSELF:
    # degraded with loss_divergence named in the findings.
    def spiked() -> None:
        state["loss"] = min(state["loss"] * spike, 1e6)
        state["gnorm"] = min(state["gnorm"] * spike, 1e6)

    degraded_reached = run_until(
        lambda: statuses[-1] == "degraded"
        and "loss_divergence" in rules_fired,
        max_s=max_s, collect_rules=True, pre=spiked)

    # phase C: lr restored — loss returns to scale, the trend windows
    # cool, and the verdict must walk back to a STABLE ok (three
    # consecutive ok ticks, so a flapping clear fails the phase)
    state["loss"], state["gnorm"] = 1.0, 2.0
    recovered = run_until(
        lambda: len(statuses) >= 3 and statuses[-3:] == ["ok"] * 3,
        min_s=0.5, max_s=max_s)
    wall = time.perf_counter() - t0

    metrics.close()
    health.reset()

    # JSONL must carry schema-valid verdicts; the run ended ok so the
    # generic SLO gate passes — but the STRICT learn gate must still
    # catch the transient divergence (recovered-but-diverged is not a
    # clean training run)
    records = load_records(jsonl)
    verdicts = [r["health/verdict"] for r in records
                if isinstance(r.get("health/verdict"), dict)]
    schema_ok = bool(verdicts) and all(
        v.get("status") in ("ok", "degraded", "critical")
        and isinstance(v.get("ok"), bool)
        and isinstance(v.get("findings"), list)
        and all(isinstance(f, dict) and "rule" in f and "key" in f
                and "severity" in f for f in v["findings"])
        for v in verdicts)
    slo = slo_problems(records)
    strict = learn_problems(records)
    strict_catches = any("loss_divergence" in p for p in strict)

    verdict = {
        "ok": (phase_a_ok and degraded_reached and recovered
               and critical_flaps[0] == 0
               and "loss_divergence" in rules_fired
               and schema_ok and not slo and strict_catches),
        "phase_a_ok": phase_a_ok,
        "degraded_reached": degraded_reached,
        "recovered": recovered,
        "critical_flaps": critical_flaps[0],
        "rules_fired": sorted(rules_fired),
        "strict_gate_catches_divergence": strict_catches,
        "learn_planes_folded": acc.planes,
        "scrapes": step[0],
        "jsonl_records": len(records),
        "verdicts_logged": len(verdicts),
        "verdict_schema_ok": schema_ok,
        "slo_problems": slo,
        "lr_spike_factor": spike,
        "wall_s": round(wall, 2),
    }
    return verdict


def run_durability_smoke(cycles: int = 20, num_actors: int = 3,
                         flushes_per_cycle: int = 4, rows: int = 8,
                         spec: str = "torn=0.35,corrupt=0.03,seed=23",
                         keep: int = 4) -> dict:
    """Kill/warm-boot loop under torn-write + wire-corruption chaos.

    Single-threaded by design: every flush is sequenced by the harness
    itself (manual ``flush_seq`` per actor), so "what must be in replay"
    is exact. After each hard kill the actors re-send their FULL history
    in original order — the flush-seq dedup absorbs everything the
    restored generation already holds, the gap lands exactly once, and
    any divergence is a real durability bug, not harness noise."""
    from distributed_deep_q_tpu.replay.replay_memory import ReplayMemory
    from distributed_deep_q_tpu.rpc import faultinject
    from distributed_deep_q_tpu.rpc.replay_server import (
        ReplayFeedClient, ReplayFeedServer)
    from distributed_deep_q_tpu.utils.durability import GenerationStore

    plan = faultinject.install(spec)
    rng = np.random.default_rng(23)
    snap = tempfile.mktemp(prefix="durability_smoke_")
    total = cycles * num_actors * flushes_per_cycle * rows
    cap = max(2 * total, 1024)

    history: dict[int, list] = {a: [] for a in range(num_actors)}
    expected: set[int] = set()
    errors: list[str] = []
    boot_mismatches: list[str] = []
    quarantined_total = checksum_total = snapshots_landed = 0

    replay = ReplayMemory(cap, (2,), np.float32, seed=0)
    server = ReplayFeedServer(replay, snapshot_path=snap, snapshot_keep=keep)

    def clients() -> list:
        host, port = server.address
        return [ReplayFeedClient(host, port, actor_id=a, timeout=5.0)
                for a in range(num_actors)]

    def push(c, seq: int, obs: np.ndarray) -> None:
        n = len(obs)
        for _ in range(200):
            try:
                resp = c.call(
                    "add_transitions", flush_seq=seq, obs=obs, next_obs=obs,
                    action=np.zeros(n, np.int32),
                    reward=np.zeros(n, np.float32),
                    discount=np.ones(n, np.float32))
            except Exception:  # noqa: BLE001 — chaos; reconnect + retry
                time.sleep(0.005)
                continue
            if resp.get("error") or resp.get("shed"):
                time.sleep(0.01)
                continue
            return
        raise RuntimeError(f"flush seq {seq} never landed")

    def probe_newest_valid():
        """Side-effect-free answer to "which generation SHOULD the next
        warm boot restore?" — same verification the server runs, but
        without quarantining, so it cannot influence the boot it checks."""
        store = GenerationStore(snap, keep=keep)
        for gen in reversed(store.generations()):
            try:
                _, meta = store.verify(gen)
                return gen, meta
            except Exception:  # noqa: BLE001 — damaged gen, keep walking
                continue
        return None

    seqs = [0] * num_actors
    t0 = time.perf_counter()
    for cycle in range(cycles):
        cs = clients()
        for _ in range(flushes_per_cycle):
            for a, c in enumerate(cs):
                seq = seqs[a]
                ids = (a * 1_000_000 + seq * 1_000
                       + np.arange(rows, dtype=np.float32))
                obs = np.stack([ids, ids], axis=1)
                push(c, seq, obs)
                history[a].append((seq, obs))
                expected.update(int(i) for i in ids)
                seqs[a] += 1
        # kill point roulette: after a sync commit / racing an async dump
        # / before any snapshot this cycle ran
        roll = rng.random()
        if roll < 0.45:
            server.snapshot(snap)
            snapshots_landed += 1
        elif roll < 0.75:
            if server.snapshot_async(snap):
                snapshots_landed += 1
            if rng.random() < 0.5:
                time.sleep(float(rng.random()) * 0.02)
        if rng.random() < 0.3:
            # crash-before-commit: a generation directory with payload
            # bytes but no manifest must be skipped by restore
            store = GenerationStore(snap, keep=keep)
            gens = store.generations()
            part = os.path.join(
                snap, f"gen-{(gens[-1] + 1 if gens else 0):08d}")
            os.makedirs(part, exist_ok=True)
            with open(os.path.join(part, "server.npz"), "wb") as f:
                f.write(bytes(rng.integers(0, 256, 64, dtype=np.uint8)))
        for c in cs:
            c.close()
        server.close()  # hard kill (no shutdown-snapshot)
        server._snap_lock.acquire()  # join any in-flight async write
        server._snap_lock.release()
        checksum_total += \
            server.telemetry.robustness_counters()["checksum_errors"]

        pick = probe_newest_valid()
        replay = ReplayMemory(cap, (2,), np.float32, seed=0)
        server = ReplayFeedServer(replay, snapshot_path=snap,
                                  snapshot_keep=keep)
        quarantined_total += \
            server.telemetry.robustness_counters()["snapshot_quarantined"]
        got = server.counters()["env_steps"]
        want = int(pick[1]["env_steps"]) if pick else 0
        if got != want or (pick and server._restored_generation != pick[0]):
            boot_mismatches.append(
                f"cycle {cycle}: booted env_steps={got} "
                f"gen={server._restored_generation}, probe says {pick}")

        cs = clients()
        for a, c in enumerate(cs):
            for seq, obs in history[a]:
                push(c, seq, obs)
        observed = replay.obs[:len(replay), 0].astype(np.int64).tolist()
        lost = len(expected - set(observed))
        duplicated = len(observed) - len(set(observed))
        corrupt_rows = len(set(observed) - expected)
        if lost or duplicated or corrupt_rows:
            errors.append(f"cycle {cycle}: lost={lost} dup={duplicated} "
                          f"corrupt_rows={corrupt_rows}")
        for c in cs:
            c.close()

    wall = time.perf_counter() - t0
    server.close()
    faultinject.uninstall()
    verdict = {
        "ok": not errors and not boot_mismatches,
        "cycles": cycles,
        "num_actors": num_actors,
        "transitions_sent": total,
        "snapshots_landed": snapshots_landed,
        "generations_quarantined": quarantined_total,
        "wire_checksum_rejections": checksum_total,
        "torn_writes_fired": plan.counters.get("file/torn", 0),
        "boot_mismatches": boot_mismatches,
        "errors": errors,
        "chaos_spec": spec,
        "wall_s": round(wall, 2),
    }
    return verdict


def run_train_chaos(argv: list[str]) -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from distributed_deep_q_tpu.compat import set_cpu_device_count
    set_cpu_device_count(2)

    from distributed_deep_q_tpu.config import apply_overrides, cartpole_config

    cfg = cartpole_config()
    cfg.mesh.backend = "cpu"
    cfg.mesh.num_fake_devices = 2
    cfg.train.total_steps = 4_000
    cfg.replay.learn_start = 500
    cfg.actors.num_actors = 1
    cfg.actors.chaos = "drop=0.005,truncate=0.003,seed=5"
    cfg.train.server_snapshot_path = tempfile.mktemp(prefix="chaos_train_")
    apply_overrides(cfg, argv)
    for arg in argv:
        print(f"override {arg}")

    from distributed_deep_q_tpu.actors.supervisor import train_distributed

    out = train_distributed(cfg, log_every=1_000)
    return {
        "env_steps": out.get("env_steps"),
        "final_return_avg100": out.get("final_return_avg100"),
        "actor_restarts": out.get("actor_restarts"),
        "actor_kill_escalations": out.get("actor_kill_escalations"),
        "rpc_dispatch_errors": out.get("rpc_dispatch_errors"),
        "rpc_duplicate_flushes": out.get("rpc_duplicate_flushes"),
    }


def run_churn_smoke(num_actors: int = 6, flushes: int = 150, rows: int = 8,
                    deadline: float = 90.0) -> dict:
    """Elastic-fleet acceptance (ISSUE 17): kill a learner host mid-run,
    add a fresh one, lose nothing.

    Two learner hosts serve a hash-assigned actor fleet; the membership
    registry rides host-0's wire. Mid-run host-1 is gracefully retired —
    its replay shard exports through the GenerationStore handoff — and a
    fresh host-2 imports the shard and joins. The fleet verdict must
    walk ok → degraded (``member_unreachable`` named) → ok with zero
    critical flaps; the health-driven autoscaler must emit
    lineage-traceable decisions into the run JSONL (shrink on the lost
    member, grow on recovery); remapped actors must reconnect through
    the resilient client (``rpc/mass_reconnects`` moves) with their
    in-flight flushes staying exactly-once across the handoff. The
    ledger gate: every labeled transition lands exactly once across the
    union of surviving shards, with zero wrong actions."""
    from distributed_deep_q_tpu import health
    from distributed_deep_q_tpu.actors import membership as ms
    from distributed_deep_q_tpu.actors.assignment import assign_fleet
    from distributed_deep_q_tpu.actors.autoscaler import (
        RECOVERY_RULE, Autoscaler)
    from distributed_deep_q_tpu.metrics import Metrics
    from distributed_deep_q_tpu.replay.replay_memory import ReplayMemory
    from distributed_deep_q_tpu.rpc import resilience
    from distributed_deep_q_tpu.rpc.replay_server import (
        ReplayFeedClient, ReplayFeedServer)
    from distributed_deep_q_tpu.rpc.resilience import (
        ResilientReplayFeedClient, RetryPolicy)

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts"))
    from telemetry_report import (
        elastic_problems, load_records, slo_problems)

    health.configure(enabled=True, fast_window_s=0.5, slow_window_s=1.5,
                     clear_ratio=0.5)
    jsonl = tempfile.mktemp(prefix="churn_smoke_", suffix=".jsonl")
    metrics = Metrics(jsonl_path=jsonl)
    total = num_actors * flushes * rows
    cap = max(2 * total, 1024)
    mass_base = resilience.mass_reconnects()

    # two learner hosts; host-0 carries the membership registry
    registry = ms.MembershipRegistry()
    replay0 = ReplayMemory(cap, (2,), np.float32, seed=0)
    server0 = ReplayFeedServer(replay0)
    server0.attach_membership(registry)
    registry.join("host-0", *server0.address)
    replay1 = ReplayMemory(cap, (2,), np.float32, seed=1)
    server1 = ReplayFeedServer(replay1)

    admin = ReplayFeedClient(*server0.address, actor_id=990, timeout=10.0)
    admin.call("fleet_join", token="host-1", host=server1.address[0],
               port=server1.address[1])
    admin.call("fleet_lease", token="host-0")  # seed host renews too
    view = admin.call("fleet_view")
    tokens = ms.view_tokens(view)
    assignment = assign_fleet(num_actors, tokens)
    owner0 = {g: t for t, gids in assignment.items() for g in gids}

    # fleet health scrapes both hosts over fresh wire connections (a
    # dead host must read as member_unreachable, not a cached verdict)
    fleet = health.FleetHealth()

    def scrape_at(addr):
        def scrape() -> dict:
            c = ReplayFeedClient(addr[0], addr[1], actor_id=991,
                                 timeout=5.0)
            try:
                return c.health()
            finally:
                c.close()
        return scrape

    fleet.register("host-0", scrape_at(server0.address))
    fleet.register("host-1", scrape_at(server1.address))

    autoscaler = Autoscaler(min_actors=2, max_actors=num_actors, step=2,
                            cooldown_s=0.5, recover_ticks=3)

    policy = RetryPolicy(base_delay=0.01, max_delay=0.3,
                         deadline=deadline)
    errors: list[str] = []
    clients: list = [None] * num_actors
    act_mod = 7  # expected action for (gid, f) is (gid*31 + f) % 7

    def actor(gid: int) -> None:
        try:
            addr = ms.view_address(view, owner0[gid])
            c = ResilientReplayFeedClient.connect(
                addr[0], addr[1], actor_id=gid, policy=policy,
                seed=200 + gid)
            clients[gid] = c
            for f in range(flushes):
                ids = gid * 1_000_000 + f * 1_000 + np.arange(
                    rows, dtype=np.float32)
                obs = np.stack([ids, ids], axis=1)
                c.add_transitions(
                    obs=obs,
                    action=np.full(rows, (gid * 31 + f) % act_mod,
                                   np.int32),
                    reward=np.zeros(rows, np.float32), next_obs=obs,
                    discount=np.ones(rows, np.float32))
                time.sleep(0.02)
            c.close()
        except Exception as e:  # noqa: BLE001 — reported in the verdict
            errors.append(f"actor {gid}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=actor, args=(g,), daemon=True)
               for g in range(num_actors)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()

    step = [0]
    statuses: list[str] = []
    critical_flaps = [0]
    rules_fired: set[str] = set()
    decisions: list[dict] = []

    def tick(collect_rules: bool = False) -> None:
        v = fleet.scrape()
        statuses.append(v.status)
        if v.status == "critical":
            critical_flaps[0] += 1
        if collect_rules and v.status != "ok":
            rules_fired.update(f.rule for f in v.findings)
        ds = [d.to_jsonable() for d in autoscaler.observe(v)]
        decisions.extend(ds)
        rec = {**fleet.gauges(), **registry.gauges(),
               **autoscaler.gauges(),
               "rpc/mass_reconnects":
                   float(resilience.mass_reconnects() - mass_base),
               "health/verdict": v.to_jsonable()}
        if ds:
            rec["autoscale/decision"] = ds
        metrics.log(step[0], **rec)
        step[0] += 1
        time.sleep(0.03)

    def run_until(pred, min_s: float = 0.0, max_s: float = 15.0,
                  collect_rules: bool = False) -> bool:
        t1 = time.monotonic()
        while True:
            tick(collect_rules)
            elapsed = time.monotonic() - t1
            if elapsed >= min_s and pred():
                return True
            if elapsed > max_s:
                return False

    max_s = deadline / 4
    # phase A: two-host steady state settles on ok
    phase_a_ok = run_until(lambda: statuses[-1] == "ok",
                           min_s=1.0, max_s=max_s)

    # phase B: retire host-1 — graceful drain + manifest-committed shard
    # export. Its scrape now fails, so the verdict must degrade with
    # member_unreachable named, and the autoscaler must shrink on it
    shard = tempfile.mktemp(prefix="churn_shard_")
    export = ms.export_shard(server1, shard)
    degraded_reached = run_until(
        lambda: statuses[-1] == "degraded"
        and "member_unreachable" in rules_fired,
        max_s=max_s, collect_rules=True)

    # phase C: host-2 imports the shard (warm boot: rows, PER state, and
    # the flush-seq dedup map all restore) and joins; host-1 leaves with
    # its shard lineage recorded. Actors re-run assign_fleet against the
    # new epoch and reconnect; in-flight resend floors come from the
    # shard's current holder so nothing double-lands
    replay2 = ReplayMemory(cap, (2,), np.float32, seed=2)
    server2, imported = ms.import_shard(replay2, shard)
    admin.call("fleet_join", token="host-2", host=server2.address[0],
               port=server2.address[1])
    admin.call("fleet_leave", token="host-1", importer="host-2")
    fleet.deregister("host-1")
    fleet.register("host-2", scrape_at(server2.address))
    handoff_lost = max(0, export["rows"] - imported["rows"])
    metrics.log(step[0], **{
        "fleet/handoff_ms": export["export_ms"] + imported["import_ms"],
        "fleet/handoff_rows": float(imported["rows"]),
        "fleet/handoff_lost_rows": float(handoff_lost)})
    step[0] += 1

    view2 = admin.call("fleet_view")
    tokens2 = ms.view_tokens(view2)
    owner2 = {g: t for t, gids in
              assign_fleet(num_actors, tokens2).items() for g in gids}
    remapped = 0
    for gid in range(num_actors):
        if owner2[gid] == owner0[gid] or clients[gid] is None:
            continue
        holder = ms.resolve_importer(view2, owner0[gid])
        if holder:
            floor = ms.resend_floor(
                *ms.view_address(view2, holder), actor_id=gid)
            clients[gid].resend_floor = max(
                clients[gid].resend_floor, floor)
        clients[gid].rehost(*ms.view_address(view2, owner2[gid]),
                            remap=True)
        remapped += 1

    # phase D: the fleet heals — stable ok, then the autoscaler's
    # recovery streak grows actor capacity back (cooldown permitting)
    recovered = run_until(
        lambda: len(statuses) >= 3 and statuses[-3:] == ["ok"] * 3,
        min_s=0.5, max_s=max_s, collect_rules=True)
    grew_back = run_until(
        lambda: any(d["action"] == "grow_actors" for d in decisions),
        max_s=max_s)

    for t in threads:
        t.join(timeout=deadline)
    hung = sum(t.is_alive() for t in threads)
    wall = time.perf_counter() - t0
    mass = resilience.mass_reconnects() - mass_base

    # labeled-frame ledger across the union of surviving shards: every
    # id exactly once, and every stored action matches its id's formula
    # (row integrity through the handoff, not just row count)
    expected = {g * 1_000_000 + f * 1_000 + r for g in range(num_actors)
                for f in range(flushes) for r in range(rows)}
    observed: list[int] = []
    wrong_actions = 0
    for rep in (replay0, replay2):
        n = len(rep)
        ids = rep.obs[:n, 0].astype(np.int64)
        observed.extend(ids.tolist())
        gids = ids // 1_000_000
        fs = (ids % 1_000_000) // 1_000
        want = (gids * 31 + fs) % act_mod
        wrong_actions += int(np.sum(rep.action[:n] != want))
    lost = len(expected) - len(set(observed))
    duplicated = len(observed) - len(set(observed))

    metrics.close()
    server0.close()
    server2.close()
    admin.close()
    health.reset()

    records = load_records(jsonl)
    slo = slo_problems(records)
    elastic = elastic_problems(records)
    shrink_named = any(d["action"] == "shrink_actors"
                       and d["rule"] == "member_unreachable"
                       for d in decisions)
    grow_named = any(d["action"] == "grow_actors"
                     and d["rule"] == RECOVERY_RULE for d in decisions)
    skipped = sum(c.resends_skipped for c in clients if c is not None)
    verdict = {
        "ok": (not errors and not hung and lost == 0 and duplicated == 0
               and wrong_actions == 0 and phase_a_ok and degraded_reached
               and recovered and grew_back and critical_flaps[0] == 0
               and handoff_lost == 0 and remapped > 0 and mass >= remapped
               and shrink_named and grow_named
               and "flush_p99" not in rules_fired
               and not slo and not elastic),
        "phase_a_ok": phase_a_ok,
        "degraded_reached": degraded_reached,
        "recovered": recovered,
        "grew_back": grew_back,
        "critical_flaps": critical_flaps[0],
        "rules_fired": sorted(rules_fired),
        "transitions_sent": total,
        "transitions_stored": len(observed),
        "lost": lost,
        "duplicated": duplicated,
        "wrong_actions": wrong_actions,
        "handoff_rows": imported["rows"],
        "handoff_lost_rows": handoff_lost,
        "handoff_ms": round(export["export_ms"]
                            + imported["import_ms"], 2),
        "restored_generation": imported["generation"],
        "actors_remapped": remapped,
        "mass_reconnects": mass,
        "resends_skipped": skipped,
        "decisions": decisions,
        "shrink_on_member_unreachable": shrink_named,
        "grow_on_recovery": grow_named,
        "fleet_epoch": registry.epoch(),
        "slo_problems": slo,
        "elastic_problems": elastic,
        "hung_actors": hung,
        "errors": errors,
        "wall_s": round(wall, 2),
    }
    return verdict


def _tenant_fleet_worker(cfg, host, port, i, stop) -> None:
    """Spawn target for the tenants-mode actor fleet (module level so
    the mp 'spawn' context can pickle it by name): stream labeled
    4-row flushes through the resilient client until told to stop.
    Column 0 carries ``f*1e3 + r`` (exact in float32 up to f≈16k —
    packing gid into the same scalar overflows after 1000 flushes),
    column 1 the actor gid, column 2 a per-process salt — a regrown
    actor reusing the gid re-labels its rows, so the parent's ledger
    can tell incarnations apart."""
    from distributed_deep_q_tpu.rpc import faultinject
    from distributed_deep_q_tpu.rpc.resilience import (
        ResilientReplayFeedClient, RetryPolicy)

    if cfg.actors.chaos:
        faultinject.install(cfg.actors.chaos)
    rows = 4
    salt = float(os.getpid() % 65536)
    c = ResilientReplayFeedClient.connect(
        host, port, actor_id=i,
        policy=RetryPolicy(base_delay=0.01, max_delay=0.2, deadline=30.0),
        seed=300 + i)
    f = 0
    while not stop.is_set():
        ids = f * 1_000 + np.arange(rows, dtype=np.float32)
        obs = np.stack([ids, np.full(rows, float(i), np.float32),
                        np.full(rows, salt, np.float32)], axis=1)
        c.add_transitions(
            obs=obs, action=np.full(rows, (i * 31 + f) % 7, np.int32),
            reward=np.zeros(rows, np.float32), next_obs=obs,
            discount=np.ones(rows, np.float32))
        f += 1
        if stop.wait(0.08):
            break
    c.close()


def _wire_retry(do, mk, tries: int = 80):
    """Land one wire call against a fresh connection per attempt —
    under chaos a drop/truncation surfaces as a transport exception
    here, and the verbs this harness sends this way are idempotent."""
    last: Exception | None = None
    for _ in range(tries):
        c = mk()
        try:
            return do(c)
        except Exception as e:  # noqa: BLE001 — chaos; retry fresh
            last = e
            time.sleep(0.02)
        finally:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
    raise RuntimeError(f"wire call never landed: {last}")


def run_tenants_smoke(deadline: float = 240.0) -> dict:
    """Close the control loop (ISSUE 20): multi-tenant degrade ladder +
    autoscaler executor, both against live process/wire state.

    See the module docstring's ``tenants`` entry for the full gate
    list. Both arcs write one JSONL, audited afterwards with
    ``telemetry_report``'s strict SLO and elastic-lineage checks."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from distributed_deep_q_tpu import health
    from distributed_deep_q_tpu.actors.autoscaler import (
        RECOVERY_RULE, Autoscaler)
    from distributed_deep_q_tpu.actors.executor import ScaleExecutor
    from distributed_deep_q_tpu.actors.supervisor import ActorSupervisor
    from distributed_deep_q_tpu.config import Config, NetConfig
    from distributed_deep_q_tpu.metrics import Metrics
    from distributed_deep_q_tpu.models.policy import BatchedPolicy
    from distributed_deep_q_tpu.replay.replay_memory import ReplayMemory
    from distributed_deep_q_tpu.rpc import faultinject
    from distributed_deep_q_tpu.rpc.flowcontrol import FlowConfig
    from distributed_deep_q_tpu.rpc.inference_server import (
        TENANT_PRIMARY, InferenceClient, InferenceServer, arm_for)
    from distributed_deep_q_tpu.rpc.replay_server import (
        ReplayFeedClient, ReplayFeedServer)

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts"))
    from telemetry_report import (
        elastic_problems, load_records, slo_problems, validate_records)

    health.configure(enabled=True, fast_window_s=0.5, slow_window_s=1.5,
                     clear_ratio=0.5)
    jsonl = tempfile.mktemp(prefix="tenants_smoke_", suffix=".jsonl")
    metrics = Metrics(jsonl_path=jsonl)
    trc = _trace_begin()
    # parent-wide wire chaos: inference clients, the burst producer, and
    # BOTH servers' accepted sockets all ride it for the whole run
    plan = faultinject.install("drop=0.015,truncate=0.01,seed=43")
    step = [0]
    t0 = time.perf_counter()
    errors: list[str] = []

    # ---- arc 1: multi-tenant inference under the degrade ladder ----------
    AB, SHADOW = "ab:cand", "shadow:next"
    arms = (TENANT_PRIMARY, AB)
    obs_dim, rows1, requests = 8, 8, 120
    net = NetConfig(kind="mlp", hidden=(32, 32), num_actions=4)

    class _StallPolicy(BatchedPolicy):
        # forward-latency lever: with `stall` set every microbatch pays
        # stall_s, so queue occupancy climbs and the ladder must walk
        # shadow → ab → primary without any synthetic shed injection
        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self.stall = threading.Event()
            self.stall_s = 0.35

        def forward(self, obs, params=None):
            if self.stall.is_set():
                time.sleep(self.stall_s)
            return super().forward(obs, params=params)

    policy1 = _StallPolicy(net, seed=7, obs_dim=obs_dim, buckets=(8,))
    ab_src = BatchedPolicy(net, seed=8, obs_dim=obs_dim, buckets=(8,))
    shadow_src = BatchedPolicy(net, seed=9, obs_dim=obs_dim, buckets=(1,))
    server1 = InferenceServer(
        policy1, max_batch=rows1, cutoff_us=2000,
        flow=FlowConfig(staged_high_watermark=80, ingest_factor=100.0,
                        flush_credit_floor=8),
        tenants=(AB, SHADOW), shed_shadow_frac=0.3, shed_ab_frac=0.55,
        ladder_burn_s=0.2)
    host1, port1 = server1.address
    server1.set_params(policy1.get_weights(), version=7)
    server1.set_params(ab_src.get_weights(), version=101, tenant=AB)
    server1.set_params(shadow_src.get_weights(), version=201, tenant=SHADOW)

    fleet1 = health.FleetHealth()
    fleet1.register("inference", server1.health_scrape)
    statuses1: list[str] = []
    critical_flaps = [0]
    tenant_slo_hits: set = set()

    def tick1(collect: bool = False) -> None:
        v = fleet1.scrape()
        statuses1.append(v.status)
        if v.status == "critical":
            critical_flaps[0] += 1
        if collect and v.status != "ok":
            for f in v.findings:
                if f.rule in ("tenant_shed", "tenant_latency") \
                        and f.key.startswith("tenant/"):
                    tenant_slo_hits.add((f.rule, f.key))
        metrics.log(step[0], **{**fleet1.gauges(),
                                **server1.telemetry_summary(),
                                "health/verdict": v.to_jsonable()})
        step[0] += 1
        time.sleep(0.05)

    def run_until1(pred, min_s: float = 0.0, max_s: float = 15.0,
                   collect: bool = False) -> bool:
        t1 = time.monotonic()
        while True:
            tick1(collect)
            elapsed = time.monotonic() - t1
            if elapsed >= min_s and pred():
                return True
            if elapsed > max_s:
                return False

    def make_obs(aid: int, i: int) -> np.ndarray:
        # labeled: a unique deterministic batch per (client, request)
        r = np.random.default_rng(1_000 * (aid + 1) + i)
        return r.standard_normal((rows1, obs_dim)).astype(np.float32)

    split_aids = list(range(7))        # hash split over (primary, ab)
    pinned_aids = list(range(100, 108))  # overload wave, pinned primary
    got: dict[int, dict] = {a: {} for a in split_aids + pinned_aids}
    sheds1: dict[int, int] = {a: 0 for a in split_aids + pinned_aids}

    def client1(aid: int, n_req: int, tenant: str = "") -> None:
        c = None
        try:
            for i in range(n_req):
                obs = make_obs(aid, i)
                for _ in range(900):
                    try:
                        if c is None:
                            c = InferenceClient(host1, port1, actor_id=aid,
                                                timeout=5.0)
                        resp = c.infer(obs, seq=i, tenant=tenant)
                    except Exception:  # noqa: BLE001 — chaos; reconnect
                        try:
                            if c is not None:
                                c.close()
                        except Exception:  # noqa: BLE001
                            pass
                        c = None
                        time.sleep(0.01)
                        continue
                    if resp.get("error"):
                        time.sleep(0.02)
                        continue
                    if resp.get("shed"):
                        sheds1[aid] += 1
                        trc.instant("shed", plane="inference")
                        time.sleep(min(
                            resp.get("retry_after_ms", 10), 50) / 1e3)
                        continue
                    if i in got[aid]:
                        errors.append(f"client {aid}: duplicate reply "
                                      f"recorded for request {i}")
                    got[aid][i] = (
                        tuple(int(a) for a in np.asarray(resp["actions"])),
                        int(resp.get("version", -1)),
                        str(resp.get("tenant", "")))
                    break
                else:
                    errors.append(f"client {aid}: request {i} never landed")
                    return
                time.sleep(0.01)
        except Exception as e:  # noqa: BLE001 — reported in the verdict
            errors.append(f"client {aid}: {type(e).__name__}: {e}")
        finally:
            try:
                if c is not None:
                    c.close()
            except Exception:  # noqa: BLE001
                pass

    threads1 = [threading.Thread(target=client1, args=(a, requests),
                                 daemon=True) for a in split_aids]
    pinned = [threading.Thread(target=client1,
                               args=(a, 40, TENANT_PRIMARY), daemon=True)
              for a in pinned_aids]
    for t in threads1:
        t.start()

    def shadow_req_count() -> float:
        return server1.telemetry.summary().get(
            f"tenant/{SHADOW}/shadow_requests", 0.0)

    # phase 1a: healthy split traffic mirrors onto the shadow tenant
    warmed = run_until1(lambda: shadow_req_count() > 0, max_s=15.0)

    # a direct request AT the shadow tenant must be refused — its
    # replies exist server-side only, they never reach an actor
    def probe_shadow(c) -> dict:
        return c.call("infer", obs=make_obs(60, 0), seq=0, tenant=SHADOW)

    rej = _wire_retry(probe_shadow,
                      lambda: InferenceClient(host1, port1, actor_id=60,
                                              timeout=5.0), tries=200)
    shadow_rejected = "mirror-only" in str(rej.get("error", ""))

    # phase 1b: stall forwards — occupancy climbs and the ladder starts
    # shedding at the bottom (shadow). The split load alone plateaus
    # around the A/B fraction, so level 2 is reached by the pinned wave
    # below; the ledger-order gate still demands shadow → ab → primary
    policy1.stall.set()
    lvl_up = run_until1(lambda: server1.ladder_level() >= 1, max_s=20.0,
                        collect=True)
    time.sleep(1.0)  # let the in-flight microbatch finish mirroring
    s1 = shadow_req_count()

    # phase 1c: a pinned-primary overload wave pushes the queue over the
    # watermark — the PRIMARY class itself must shed, completing the
    # strict ladder order
    for t in pinned:
        t.start()
    prim_shed = run_until1(
        lambda: any(e["class"] == "primary"
                    for e in server1.ladder_ledger()),
        max_s=20.0, collect=True)
    s2 = shadow_req_count()

    # phase 1d: release the stall; the fleet must walk back to ok and
    # the ladder back to level 0 under a light primary probe
    policy1.stall.clear()
    for t in threads1 + pinned:
        t.join(timeout=deadline / 2)
    hung1 = sum(t.is_alive() for t in threads1 + pinned)
    ladder_cleared = False
    pc = None
    t_end = time.monotonic() + 10.0
    i_probe = 0
    while time.monotonic() < t_end:
        try:
            if pc is None:
                pc = InferenceClient(host1, port1, actor_id=50, timeout=5.0)
            pc.infer(make_obs(50, i_probe), seq=i_probe,
                     tenant=TENANT_PRIMARY)
            i_probe += 1
        except Exception:  # noqa: BLE001 — chaos; reconnect
            try:
                if pc is not None:
                    pc.close()
            except Exception:  # noqa: BLE001
                pass
            pc = None
        if server1.ladder_level() == 0:
            ladder_cleared = True
            break
        time.sleep(0.05)
    if pc is not None:
        try:
            pc.close()
        except Exception:  # noqa: BLE001
            pass
    recovered1 = run_until1(lambda: statuses1[-1] == "ok", min_s=0.5,
                            max_s=20.0, collect=True)

    tm1 = server1.telemetry_summary()
    ledger = server1.ladder_ledger()
    server1.close()

    # per-arm oracle replay: every reply must carry the RIGHT arm's
    # action and θ version for that exact observation
    oracle_p = BatchedPolicy(net, seed=7, obs_dim=obs_dim, buckets=(8,))
    wrong = missing = tenant_mm = version_mm = 0
    for aid in got:
        arm = TENANT_PRIMARY if aid >= 100 else arm_for(aid, arms)
        oracle = oracle_p if arm == TENANT_PRIMARY else ab_src
        want_ver = 7 if arm == TENANT_PRIMARY else 101
        n_req = 40 if aid >= 100 else requests
        for i in range(n_req):
            rec = got[aid].get(i)
            if rec is None:
                missing += 1
                continue
            acts, ver, ten = rec
            if ten != arm:
                tenant_mm += 1
            if ver != want_ver:
                version_mm += 1
            want, _ = oracle.forward(make_obs(aid, i))
            if acts != tuple(int(a) for a in np.asarray(want)):
                wrong += 1

    # ---- arc 2: autoscaler executor closes the loop on processes ---------
    replay2 = ReplayMemory(65536, (3,), np.float32, seed=0)
    rserver = ReplayFeedServer(
        replay2, flow=FlowConfig(ingest_factor=1.5, flush_credit_floor=8,
                                 rate_halflife_s=0.5,
                                 max_retry_after_s=0.05))
    host2, port2 = rserver.address

    consumer_stop = threading.Event()

    def consumer() -> None:
        # rate-capped learner stand-in: consumption rate is what the
        # admission controller's ingest_factor is measured against
        while not consumer_stop.is_set():
            with rserver.replay_lock:
                if len(replay2) >= 32:
                    replay2.sample(32)
                    sampled = True
                else:
                    sampled = False
            if sampled:
                rserver.note_consumed(32)
            time.sleep(32 / 600.0)

    consumer_t = threading.Thread(target=consumer, daemon=True)
    consumer_t.start()

    cfg2 = Config()
    cfg2.actors.num_actors = 3
    cfg2.actors.chaos = "drop=0.03,delay=0.05:30,seed=11"
    sup = ActorSupervisor(cfg2, host2, port2, heartbeat_timeout=30.0,
                          spawn_grace=60.0, target=_tenant_fleet_worker)
    sup.start()

    fleet2 = health.FleetHealth()
    fleet2.register("replay", rserver.health_scrape)
    autoscaler2 = Autoscaler(min_actors=2, max_actors=3, step=1,
                             cooldown_s=0.3, recover_ticks=2)
    executor = ScaleExecutor(
        sup, rate_limit_s=0.25, drain_s=1.0, spawn_grace_s=30.0,
        heartbeat_ok=lambda i: (rserver.last_seen.get(i, 0.0)
                                > sup.spawned_at.get(i, float("inf"))),
        stream_seq=rserver.stream_seq_of,
        retire_stream=rserver.retire_stream)

    statuses2: list[str] = []
    rules2: set[str] = set()
    decisions2: list[dict] = []
    applied_all: list[dict] = []

    def tick2(collect: bool = False) -> None:
        v = fleet2.scrape()
        statuses2.append(v.status)
        if v.status == "critical":
            critical_flaps[0] += 1
        if collect and v.status != "ok":
            rules2.update(f.rule for f in v.findings)
        ds = autoscaler2.observe(v)
        applied = executor.apply(ds)
        ds_j = [d.to_jsonable() for d in ds]
        decisions2.extend(ds_j)
        rec = {**fleet2.gauges(), **autoscaler2.gauges(),
               **executor.gauges(), "health/verdict": v.to_jsonable()}
        if ds_j:
            rec["autoscale/decision"] = ds_j
        if applied:
            rec["autoscale/applied"] = applied
            applied_all.extend(applied)
        metrics.log(step[0], **rec)
        step[0] += 1
        time.sleep(0.05)

    def run_until2(pred, min_s: float = 0.0, max_s: float = 30.0,
                   collect: bool = False) -> bool:
        t1 = time.monotonic()
        while True:
            tick2(collect)
            elapsed = time.monotonic() - t1
            if elapsed >= min_s and pred():
                return True
            if elapsed > max_s:
                return False

    # phase 2a: the spawned fleet comes up and lands flushes
    booted = run_until2(
        lambda: statuses2[-1] == "ok"
        and all(rserver.stream_seq_of(i) >= 0 for i in range(3)),
        min_s=0.5, max_s=60.0)

    # phase 2b: a burst producer outruns the consumer — ingest_shed
    # burns, the autoscaler shrinks, and the executor retires a REAL
    # process (drain, terminate, dedup-stamp eviction)
    burst_stop = threading.Event()
    burst_sheds = [0]

    def burst() -> None:
        # the raw stub, on purpose: the resilient client's credit token
        # bucket paces a producer to its fair share, so a "burst" riding
        # it reaches equilibrium and never trips admission. This loop
        # ignores credits and hammers; it still resends the SAME
        # flush_seq until the server acks (ok or duplicate), so the
        # server-side dedup stamp keeps the ledger exactly-once
        c: ReplayFeedClient | None = None
        f = 0
        sheds = 0
        while not burst_stop.is_set():
            ids = f * 1_000 + np.arange(256, dtype=np.float32)
            obs = np.stack([ids, np.full(256, 9.0, np.float32),
                            np.zeros(256, np.float32)], axis=1)
            while not burst_stop.is_set():
                try:
                    if c is None:
                        c = ReplayFeedClient(host2, port2, actor_id=9,
                                             timeout=5.0)
                    resp = c.call(
                        "add_transitions", flush_seq=f, obs=obs,
                        action=np.full(256, (9 * 31 + f) % 7, np.int32),
                        reward=np.zeros(256, np.float32), next_obs=obs,
                        discount=np.ones(256, np.float32))
                except Exception:  # noqa: BLE001 — chaos; resend same f
                    if c is not None:
                        try:
                            c.close()
                        except Exception:  # noqa: BLE001
                            pass
                        c = None
                    continue
                if resp.get("shed"):
                    sheds += 1
                    trc.instant("shed", plane="replay")
                    time.sleep(0.05)
                    continue
                if resp.get("error"):
                    time.sleep(0.02)
                    continue
                break
            f += 1
        burst_sheds[0] = sheds
        if c is not None:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass

    burst_t = threading.Thread(target=burst, daemon=True)
    burst_t.start()
    shrunk = run_until2(
        lambda: any(a["action"] == "retire" and a["applied"]
                    for a in applied_all),
        max_s=40.0, collect=True)
    burst_stop.set()
    burst_t.join(timeout=60.0)
    retired_ok = (sup.fleet_size() == 2
                  and rserver.stream_seq_of(2) == -1
                  and sup.executor_terminations == 1
                  and sup.kill_escalations == 0)

    # the eviction verb itself must be resend-safe on the wire: two
    # literal retire_stream calls, each on a fresh chaos-wrapped
    # connection, must both land as the same no-op
    def mk2():
        return ReplayFeedClient(host2, port2, actor_id=2, timeout=5.0)

    r1 = _wire_retry(lambda c: c.call("retire_stream"), mk2)
    r2 = _wire_retry(lambda c: c.call("retire_stream"), mk2)
    evict_idempotent = (bool(r1.get("ok")) and bool(r2.get("ok"))
                        and rserver.stream_seq_of(2) == -1)

    # phase 2c: the pressure is gone — the recovery streak must grow
    # the retired slot back and the fleet must land converged
    regrew = run_until2(
        lambda: any(a["action"] == "grow" and a["applied"]
                    for a in applied_all),
        max_s=60.0, collect=True)
    settled = run_until2(
        lambda: sup.fleet_size() == 3 and rserver.stream_seq_of(2) >= 0
        and statuses2[-1] == "ok",
        min_s=0.5, max_s=60.0)

    sup.stop()
    consumer_stop.set()
    consumer_t.join(timeout=10.0)
    shed_flushes = rserver.telemetry_summary().get("rpc/shed_flushes", 0.0)
    rollbacks = executor.gauges()["autoscale/rollbacks"]
    rserver.close()
    metrics.close()
    health.reset()
    faultinject.uninstall()
    wall = time.perf_counter() - t0

    # labeled ledger over the replay ring: exactly-once per (id, salt)
    # incarnation, every stored action matching its id's formula. No
    # loss gate — the workers are open-ended and one was deliberately
    # terminated mid-stream
    n = len(replay2)
    ids = replay2.obs[:n, 0].astype(np.int64)
    gids = replay2.obs[:n, 1].astype(np.int64)
    salts = replay2.obs[:n, 2].astype(np.int64)
    pairs = list(zip(ids.tolist(), gids.tolist(), salts.tolist()))
    duplicated = len(pairs) - len(set(pairs))
    fs = ids // 1_000
    wrong2 = int(np.sum(replay2.action[:n] != (gids * 31 + fs) % 7))

    records = load_records(jsonl)
    slo = slo_problems(records)
    elastic = elastic_problems(records)
    invalid = validate_records(records)
    shrink_named = any(d["action"] == "shrink_actors"
                       and d["rule"] == "ingest_shed" for d in decisions2)
    grow_named = any(d["action"] == "grow_actors"
                     and d["rule"] == RECOVERY_RULE for d in decisions2)
    retire_applied = any(a["action"] == "retire" and a["applied"]
                         and a["actor_id"] == 2 for a in applied_all)
    grow_applied = any(a["action"] == "grow" and a["applied"]
                       and a["actor_id"] == 2 for a in applied_all)
    ledger_classes = [e["class"] for e in ledger]
    total_sheds1 = sum(sheds1.values())
    verdict = {
        "ok": (not errors and hung1 == 0 and wrong == 0 and missing == 0
               and tenant_mm == 0 and version_mm == 0
               and warmed and lvl_up and prim_shed and shadow_rejected
               and s1 > 0 and s2 == s1
               and ledger_classes == ["shadow", "ab", "primary"]
               and ladder_cleared and recovered1
               and len(tenant_slo_hits) > 0
               and tm1.get("inference/compiled_buckets", 0) <= 1
               and booted and shrunk and retired_ok and evict_idempotent
               and regrew and settled and shrink_named and grow_named
               and retire_applied and grow_applied and rollbacks == 0
               and duplicated == 0 and wrong2 == 0
               and critical_flaps[0] == 0
               and not slo and not elastic and not invalid),
        # arc 1 — multi-tenant serving
        "replies": sum(len(g) for g in got.values()),
        "wrong_actions": wrong,
        "missing_actions": missing,
        "tenant_mismatches": tenant_mm,
        "version_mismatches": version_mm,
        "client_sheds": total_sheds1,
        "ladder_ledger": ledger,
        "ladder_cleared": ladder_cleared,
        "shadow_requests": s1,
        "shadow_frozen_under_shed": s2 == s1,
        "shadow_direct_rejected": shadow_rejected,
        "tenant_slo_findings": sorted(map(list, tenant_slo_hits)),
        "compiled_buckets": tm1.get("inference/compiled_buckets", 0),
        "tenants_served": tm1.get("tenant/served", 0),
        "inference_recovered": recovered1,
        # arc 2 — autoscaler executor
        "booted": booted,
        "shrunk": shrunk,
        "regrew": regrew,
        "settled": settled,
        "shrink_on_ingest_shed": shrink_named,
        "grow_on_recovery": grow_named,
        "retire_applied": retire_applied,
        "grow_applied": grow_applied,
        "evict_idempotent": evict_idempotent,
        "executor_terminations": sup.executor_terminations,
        "kill_escalations": sup.kill_escalations,
        "rollbacks": rollbacks,
        "burst_sheds": burst_sheds[0],
        "shed_flushes": shed_flushes,
        "rules_fired": sorted(rules2),
        "decisions": decisions2,
        "applied": applied_all,
        "transitions_stored": n,
        "duplicated": duplicated,
        "wrong_stored_actions": wrong2,
        # shared gates
        "critical_flaps": critical_flaps[0],
        "slo_problems": slo,
        "elastic_problems": elastic,
        "invalid_records": invalid,
        "faults_fired": dict(sorted(plan.counters.items())),
        "hung_clients": hung1,
        "errors": errors,
        "wall_s": round(wall, 2),
    }
    trace = _trace_verdict(trc)
    verdict["trace"] = trace
    verdict["ok"] = (verdict["ok"] and trace["orphan_spans"] == 0
                     and (total_sheds1 == 0
                          or trace["instants"].get("shed", 0) > 0))
    return verdict


def _require_clean_gate() -> None:
    """Chaos results must never be reported for code with known race
    findings — refuse to run unless the static-analysis gate is clean."""
    from distributed_deep_q_tpu.analysis import run_all

    findings = run_all()
    if findings:
        for f in findings:
            print(f, file=sys.stderr)
        print(f"chaos_smoke: REFUSING to run — analysis gate failed with "
              f"{len(findings)} finding(s); fix or suppress them first "
              "(python scripts/analysis_gate.py)", file=sys.stderr)
        sys.exit(2)


if __name__ == "__main__":
    _require_clean_gate()
    args = sys.argv[1:]
    if args and args[0] == "train":
        print(json.dumps(run_train_chaos(args[1:]), default=str))
        sys.exit(0)
    if args and args[0] in ("health", "--health"):
        verdict = run_health_smoke(
            spec=args[1] if len(args) > 1 else "corrupt=0.35,seed=41")
        print(json.dumps(verdict))
        sys.exit(0 if verdict["ok"] else 1)
    if args and args[0] in ("learn", "--learn", "divergence"):
        kwargs = {}
        if len(args) > 1:
            kwargs["spike"] = float(args[1])
        verdict = run_learn_divergence_smoke(**kwargs)
        print(json.dumps(verdict))
        sys.exit(0 if verdict["ok"] else 1)
    if args and args[0] in ("churn", "--churn", "elastic"):
        kwargs = {}
        if len(args) > 1 and args[1].isdigit():
            kwargs["num_actors"] = int(args[1])
        verdict = run_churn_smoke(**kwargs)
        print(json.dumps(verdict))
        sys.exit(0 if verdict["ok"] else 1)
    if args and args[0] in ("tenants", "--tenants"):
        verdict = run_tenants_smoke()
        print(json.dumps(verdict))
        sys.exit(0 if verdict["ok"] else 1)
    if args and args[0] in ("durability", "--durability"):
        kwargs = {}
        if len(args) > 1 and args[1].isdigit():
            kwargs["cycles"] = int(args[1])
        if len(args) > 2:
            kwargs["spec"] = args[2]
        verdict = run_durability_smoke(**kwargs)
        print(json.dumps(verdict))
        sys.exit(0 if verdict["ok"] else 1)
    if args and args[0] in ("vector", "--vector"):
        verdict = run_vector_chaos_smoke(
            spec=args[1] if len(args) > 1
            else "drop=0.02,truncate=0.01,seed=31")
        print(json.dumps(verdict))
        sys.exit(0 if verdict["ok"] else 1)
    if args and args[0] in ("inference", "--inference"):
        verdict = run_inference_chaos_smoke(
            spec=args[1] if len(args) > 1
            else "drop=0.03,truncate=0.02,corrupt=0.01,seed=29")
        print(json.dumps(verdict))
        sys.exit(0 if verdict["ok"] else 1)
    if args and args[0] in ("ingest", "--ingest", "saturation"):
        verdict = run_ingest_saturation_smoke(
            spec=args[1] if len(args) > 1 else "delay=0.05:20,seed=17")
        print(json.dumps(verdict))
        sys.exit(0 if verdict["ok"] else 1)
    if args and args[0] in ("overload", "--overload"):
        verdict = run_overload_smoke(
            spec=args[1] if len(args) > 1 else "delay=0.05:20,seed=13")
        print(json.dumps(verdict))
        sys.exit(0 if verdict["ok"] else 1)
    n, spec = 4, "drop=0.03,truncate=0.02,seed=11"
    for arg in args:
        if arg.isdigit():
            n = int(arg)
        else:
            spec = arg
    verdict = run_chaos_smoke(num_actors=n, spec=spec)
    print(json.dumps(verdict))
    sys.exit(0 if verdict["ok"] else 1)
