"""Chaos smoke — prove the RPC fault-tolerance stack end to end.

Three modes:

``python scripts/chaos_smoke.py [num_actors] [spec]`` (default)
    Threaded actor fleet over the production wire protocol: resilient
    clients stream LABELED transitions into a ``ReplayFeedServer`` while
    the chaos shim drops and truncates connections on both sides, and the
    learner is killed and warm-rebooted from its snapshot mid-run on the
    same port. Prints one JSON verdict line; exit status 1 if any
    transition was lost or duplicated. Fast (seconds), CPU-only, no jax —
    runnable on any box as a release gate for the resilience plane.

``python scripts/chaos_smoke.py overload [spec]``
    Overload acceptance (ISSUE 5): a producer fleet deliberately outruns a
    rate-capped consumer, so the server's admission controller must shed —
    the gate is *shed but never lost*: every transition lands exactly once
    (the shed flush re-stages under its original ``flush_seq``), sheds
    actually fired, and the clients' token buckets paced to the granted
    credits. Chaos delays compose on top via the optional spec.

``python scripts/chaos_smoke.py train [cfg.overrides ...]``
    The full distributed trainer (spawned actor processes, mesh learner)
    on CartPole with chaos enabled via ``cfg.actors.chaos`` — the env-var
    propagation path the fleet uses in production. Slower (jax import per
    spawned child); prints the run summary with the robustness counters
    (restarts, kill escalations, dispatch errors, duplicate flushes).

Thread actors in the default mode for the same reason as
``fleet_smoke.py``: the RPC boundary is what's under test, and labeled
payloads make loss/duplication decidable exactly.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_chaos_smoke(num_actors: int = 4, flushes: int = 120, rows: int = 8,
                    spec: str = "drop=0.03,truncate=0.02,seed=11",
                    deadline: float = 120.0) -> dict:
    from distributed_deep_q_tpu.replay.replay_memory import ReplayMemory
    from distributed_deep_q_tpu.rpc import faultinject
    from distributed_deep_q_tpu.rpc.replay_server import ReplayFeedServer
    from distributed_deep_q_tpu.rpc.resilience import (
        ResilientReplayFeedClient, RetryPolicy)

    plan = faultinject.install(spec)
    snap = tempfile.mktemp(prefix="chaos_smoke_")
    total = num_actors * flushes * rows
    replay = ReplayMemory(max(2 * total, 1024), (2,), np.float32, seed=0)
    server = ReplayFeedServer(replay)
    host, port = server.address
    policy = RetryPolicy(base_delay=0.01, max_delay=0.2, deadline=deadline)
    errors: list[str] = []
    retries = [0] * num_actors

    def actor(aid: int) -> None:
        try:
            c = ResilientReplayFeedClient.connect(
                host, port, actor_id=aid, policy=policy, seed=100 + aid)
            for f in range(flushes):
                ids = aid * 1_000_000 + f * 1_000 + np.arange(
                    rows, dtype=np.float32)
                obs = np.stack([ids, ids], axis=1)
                c.add_transitions(
                    obs=obs, action=np.zeros(rows, np.int32),
                    reward=np.zeros(rows, np.float32), next_obs=obs,
                    discount=np.ones(rows, np.float32))
                time.sleep(0.001)
            retries[aid] = c.retries
            c.close()
        except Exception as e:  # noqa: BLE001 — reported in the verdict
            errors.append(f"actor {aid}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=actor, args=(a,), daemon=True)
               for a in range(num_actors)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()

    # kill + warm-reboot the learner once about half the traffic landed
    t_end = time.monotonic() + deadline / 2
    while server.counters()["env_steps"] < total // 2 \
            and time.monotonic() < t_end:
        time.sleep(0.01)
    server.shutdown(snap)
    replay2 = ReplayMemory(max(2 * total, 1024), (2,), np.float32, seed=0)
    server = ReplayFeedServer(replay2, host=host, port=port,
                              snapshot_path=snap)

    for t in threads:
        t.join(timeout=deadline)
    hung = sum(t.is_alive() for t in threads)
    wall = time.perf_counter() - t0
    rpc = server.telemetry.robustness_counters()

    expected = {a * 1_000_000 + f * 1_000 + r for a in range(num_actors)
                for f in range(flushes) for r in range(rows)}
    observed = replay2.obs[:len(replay2), 0].astype(np.int64).tolist()
    lost = len(expected) - len(set(observed))
    duplicated = len(observed) - len(set(observed))
    verdict = {
        "ok": not errors and not hung and lost == 0 and duplicated == 0,
        "num_actors": num_actors,
        "transitions_sent": total,
        "transitions_stored": len(observed),
        "lost": lost,
        "duplicated": duplicated,
        "chaos_spec": spec,
        "faults_fired": dict(sorted(plan.counters.items())),
        "client_retries": sum(retries),
        "duplicate_flushes_absorbed": rpc["duplicate_flushes"],
        "dispatch_errors": rpc["dispatch_errors"],
        "hung_actors": hung,
        "errors": errors,
        "wall_s": round(wall, 2),
    }
    server.close()
    faultinject.uninstall()
    return verdict


def run_overload_smoke(num_actors: int = 3, flushes: int = 40, rows: int = 16,
                       spec: str = "delay=0.05:20,seed=13",
                       consume_rate: float = 300.0,
                       deadline: float = 120.0) -> dict:
    """Producer fleet ~10× faster than a rate-capped consumer: the server
    MUST shed, and the gate is shed-but-never-lost — exactly-once delivery
    of every labeled transition despite admission control plus chaos."""
    from distributed_deep_q_tpu.replay.replay_memory import ReplayMemory
    from distributed_deep_q_tpu.rpc import faultinject
    from distributed_deep_q_tpu.rpc.flowcontrol import FlowConfig
    from distributed_deep_q_tpu.rpc.replay_server import ReplayFeedServer
    from distributed_deep_q_tpu.rpc.resilience import (
        ResilientReplayFeedClient, RetryPolicy)

    plan = faultinject.install(spec) if spec else None
    total = num_actors * flushes * rows
    replay = ReplayMemory(max(2 * total, 1024), (2,), np.float32, seed=0)
    # tight ingest_factor so the mismatch branch trips as soon as the
    # consumer's rate is observable; floor small enough to actually pace
    flow = FlowConfig(ingest_factor=1.5, flush_credit_floor=8,
                      rate_halflife_s=0.5)
    server = ReplayFeedServer(replay, flow=flow)
    host, port = server.address
    policy = RetryPolicy(base_delay=0.01, max_delay=0.2, deadline=deadline)
    errors: list[str] = []
    stop = threading.Event()
    clients: list = [None] * num_actors

    def consumer() -> None:
        # rate-capped learner stand-in: sample under the server's lock,
        # feed the flow controller's consumption EWMA
        batch = 32
        while not stop.is_set():
            with server.replay_lock:
                ready = len(replay) >= batch
                if ready:
                    replay.sample(batch)
            if ready:
                server.note_consumed(batch)
                time.sleep(batch / consume_rate)
            else:
                time.sleep(0.005)

    def actor(aid: int) -> None:
        try:
            c = ResilientReplayFeedClient.connect(
                host, port, actor_id=aid, policy=policy, seed=200 + aid)
            clients[aid] = c
            for f in range(flushes):  # no pacing: outrun the consumer
                ids = aid * 1_000_000 + f * 1_000 + np.arange(
                    rows, dtype=np.float32)
                obs = np.stack([ids, ids], axis=1)
                c.add_transitions(
                    obs=obs, action=np.zeros(rows, np.int32),
                    reward=np.zeros(rows, np.float32), next_obs=obs,
                    discount=np.ones(rows, np.float32))
            c.close()
        except Exception as e:  # noqa: BLE001 — reported in the verdict
            errors.append(f"actor {aid}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=actor, args=(a,), daemon=True)
               for a in range(num_actors)]
    drain = threading.Thread(target=consumer, daemon=True)
    t0 = time.perf_counter()
    drain.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=deadline)
    hung = sum(t.is_alive() for t in threads)
    stop.set()
    drain.join(timeout=5)
    wall = time.perf_counter() - t0

    rpc = server.telemetry.robustness_counters()
    fc = server.flow_counters()
    expected = {a * 1_000_000 + f * 1_000 + r for a in range(num_actors)
                for f in range(flushes) for r in range(rows)}
    observed = replay.obs[:len(replay), 0].astype(np.int64).tolist()
    lost = len(expected) - len(set(observed))
    duplicated = len(observed) - len(set(observed))
    client_sheds = sum(c.sheds for c in clients if c is not None)
    throttled = sum(c.throttled_s for c in clients if c is not None)
    verdict = {
        # the acceptance: overload produced sheds AND nothing was lost or
        # duplicated — backpressure is explicit cooperation, not data loss
        "ok": (not errors and not hung and lost == 0 and duplicated == 0
               and rpc["shed_flushes"] > 0),
        "num_actors": num_actors,
        "transitions_sent": total,
        "transitions_stored": len(observed),
        "lost": lost,
        "duplicated": duplicated,
        "shed_flushes": rpc["shed_flushes"],
        "client_sheds": client_sheds,
        "client_throttled_s": round(throttled, 3),
        "duplicate_flushes_absorbed": rpc["duplicate_flushes"],
        "degraded_trips": fc["degraded_trips"],
        "consume_rate_cap": consume_rate,
        "chaos_spec": spec,
        "faults_fired": dict(sorted(plan.counters.items())) if plan else {},
        "hung_actors": hung,
        "errors": errors,
        "wall_s": round(wall, 2),
    }
    server.close()
    faultinject.uninstall()
    return verdict


def run_train_chaos(argv: list[str]) -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from distributed_deep_q_tpu.compat import set_cpu_device_count
    set_cpu_device_count(2)

    from distributed_deep_q_tpu.config import apply_overrides, cartpole_config

    cfg = cartpole_config()
    cfg.mesh.backend = "cpu"
    cfg.mesh.num_fake_devices = 2
    cfg.train.total_steps = 4_000
    cfg.replay.learn_start = 500
    cfg.actors.num_actors = 1
    cfg.actors.chaos = "drop=0.005,truncate=0.003,seed=5"
    cfg.train.server_snapshot_path = tempfile.mktemp(prefix="chaos_train_")
    apply_overrides(cfg, argv)
    for arg in argv:
        print(f"override {arg}")

    from distributed_deep_q_tpu.actors.supervisor import train_distributed

    out = train_distributed(cfg, log_every=1_000)
    return {
        "env_steps": out.get("env_steps"),
        "final_return_avg100": out.get("final_return_avg100"),
        "actor_restarts": out.get("actor_restarts"),
        "actor_kill_escalations": out.get("actor_kill_escalations"),
        "rpc_dispatch_errors": out.get("rpc_dispatch_errors"),
        "rpc_duplicate_flushes": out.get("rpc_duplicate_flushes"),
    }


def _require_clean_gate() -> None:
    """Chaos results must never be reported for code with known race
    findings — refuse to run unless the static-analysis gate is clean."""
    from distributed_deep_q_tpu.analysis import run_all

    findings = run_all()
    if findings:
        for f in findings:
            print(f, file=sys.stderr)
        print(f"chaos_smoke: REFUSING to run — analysis gate failed with "
              f"{len(findings)} finding(s); fix or suppress them first "
              "(python scripts/analysis_gate.py)", file=sys.stderr)
        sys.exit(2)


if __name__ == "__main__":
    _require_clean_gate()
    args = sys.argv[1:]
    if args and args[0] == "train":
        print(json.dumps(run_train_chaos(args[1:]), default=str))
        sys.exit(0)
    if args and args[0] in ("overload", "--overload"):
        verdict = run_overload_smoke(
            spec=args[1] if len(args) > 1 else "delay=0.05:20,seed=13")
        print(json.dumps(verdict))
        sys.exit(0 if verdict["ok"] else 1)
    n, spec = 4, "drop=0.03,truncate=0.02,seed=11"
    for arg in args:
        if arg.isdigit():
            n = int(arg)
        else:
            spec = arg
    verdict = run_chaos_smoke(num_actors=n, spec=spec)
    print(json.dumps(verdict))
    sys.exit(0 if verdict["ok"] else 1)
