"""Multi-host sharded-replay bench worker — spawned by bench.py.

One simulated learner host of an N-host multi-controller run (ISSUE 10
``multihost_curve``). Every host owns a FULL local data plane: its
replay shard slice of the global device ring, a local ``ReplayFeedServer``
fed only by its consistent-hash-assigned writers (actors/assignment.py),
the shard-aware ingest drain, local PER sampling, and per-shard priority
write-back. The single cross-host interaction is the ``lax.pmean``
inside the fused train step (plus the lockstep-flush round agreement,
a scalar MAX) — which is exactly what the curve measures.

Workload is FIXED GLOBALLY across host counts (strong scaling): global
batch, global ring capacity, global device count, and the global ingest
target are constants; each of the N hosts carries 1/N of every plane.
On a real pod each host has its own chips, so the wall step rate would
hold flat as N grows; this container time-slices all N processes on the
SAME cores, so the honest headline per point is the AGGREGATE per-host
plane throughput (wall steps/s x N). That aggregate is linear in N iff
the sharing overhead — the allreduce plus lockstep agreement — stays
small; any cross-host replay traffic or O(global) per-host work would
crater it. bench.py records both the wall and the aggregate rate.

Collective discipline: every process runs the SAME dispatch counts
(warmup / settle / reps, with the per-rep dispatch count agreed via
``global_max_int``), so the in-step pmean and the flush round agreement
always pair up across hosts. All host-local work (prepare_rounds in the
drain, RPC serving, pacing) stays off the collective path.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# fixed GLOBAL workload — identical at every host count (strong scaling)
DEVICES = 4          # global dp mesh size (virtual CPU devices)
BATCH = 64           # global train batch
CAPACITY = 8192      # global frame-ring capacity
STREAMS = 2          # writer streams PER HOST (fleet = STREAMS * n_hosts)
CHAIN = 8            # fused grad steps per dispatch
FRAME = (36, 36)     # Nature conv stack minimum — the dry-run shape
WRITE_CHUNK = 32
PREFILL_PER_HOST = 480
REPS = 5


def _writer(client, stop, rate_tps: float, seed: int, counter, ci: int,
            errs: list):
    """Paced RPC writer for one local stream — frames/actions/rewards
    from the stream's own rng, short episodes so slots seal steadily."""
    rng = np.random.default_rng(seed)
    # big batches: on this synchronous-CPU fallback the dispatch loop
    # holds the replay lock for nearly the whole step, so each inter-
    # dispatch yield admits ~one RPC per writer — the rows it carries
    # set the achievable ingest rate
    rows = 256
    period = rows / max(rate_tps, 1e-6)
    nxt = time.perf_counter()
    while not stop.is_set():
        batch = {
            "frame": rng.integers(0, 255, (rows,) + FRAME, dtype=np.uint8),
            "action": rng.integers(0, 4, rows).astype(np.int32),
            "reward": rng.standard_normal(rows).astype(np.float32),
            "done": (rng.random(rows) < 1 / 9).astype(bool),
        }
        try:
            resp = client.add_transitions(**batch)
        except Exception:
            if not stop.is_set():  # teardown races are expected
                import traceback
                errs.append(traceback.format_exc())
            return
        if resp.get("ok"):
            counter[ci] += rows
        nxt += period
        delay = nxt - time.perf_counter()
        if delay > 0:
            stop.wait(delay)


def main() -> None:
    pid, nproc = int(sys.argv[1]), int(sys.argv[2])
    port, out_path = sys.argv[3], sys.argv[4]
    target_tps = float(sys.argv[5])  # GLOBAL ingest target, split /nproc

    from distributed_deep_q_tpu.config import (
        Config, MeshConfig, NetConfig, ReplayConfig)
    from distributed_deep_q_tpu.parallel.multihost import (
        all_processes_ready, global_max_int, initialize_multihost)

    mesh_cfg = MeshConfig(backend="cpu", num_fake_devices=DEVICES,
                          dp=DEVICES, coordinator=f"127.0.0.1:{port}",
                          num_processes=nproc, process_id=pid)
    if nproc == 1:
        # single-host reference point: initialize_multihost is a no-op,
        # pin the platform + device count the conftest way
        import jax
        jax.config.update("jax_platforms", "cpu")
        from distributed_deep_q_tpu.compat import set_cpu_device_count
        set_cpu_device_count(DEVICES, exact=True)
    initialize_multihost(mesh_cfg)

    import jax

    # NO persistent compile cache here, deliberately: executables
    # deserialized from bench.py's .jax_cache segfault inside the gloo
    # collectives on the multi-process CPU backend (reproduced at 4
    # hosts: fresh compiles pass 3/3, cache hits SIGSEGV the leader).
    # The tiny curve shapes recompile in seconds; correctness wins.

    from distributed_deep_q_tpu.actors.assignment import local_slice
    from distributed_deep_q_tpu.replay.device_per import DevicePERFrameReplay
    from distributed_deep_q_tpu.rpc.replay_server import (
        ReplayFeedClient, ReplayFeedServer)
    from distributed_deep_q_tpu.solver import Solver

    cfg = Config()
    cfg.mesh = mesh_cfg
    cfg.net = NetConfig(kind="nature_cnn", num_actions=4, frame_shape=FRAME)
    cfg.replay = ReplayConfig(capacity=CAPACITY, batch_size=BATCH, n_step=2,
                              prioritized=True, device_per=True,
                              write_chunk=WRITE_CHUNK)
    solver = Solver(cfg)
    replay = DevicePERFrameReplay(cfg.replay, solver.mesh, FRAME, stack=4,
                                  gamma=0.99, seed=0,
                                  write_chunk=WRITE_CHUNK,
                                  num_streams=STREAMS)

    # prefill this host's streams directly (no pacing), then one lockstep
    # flush drains every staged round on every host
    rng = np.random.default_rng(1000 + pid)
    per_stream = PREFILL_PER_HOST // STREAMS
    for s in range(STREAMS):
        replay.add_batch({
            "frame": rng.integers(0, 255, (per_stream,) + FRAME,
                                  dtype=np.uint8),
            "action": rng.integers(0, 4, per_stream).astype(np.int32),
            "reward": rng.standard_normal(per_stream).astype(np.float32),
            "done": (np.arange(per_stream) % 9 == 8),
        }, stream=s)
    replay.flush()
    assert all_processes_ready(replay.ready(BATCH)), \
        "prefill left a shard empty — every host must be sampleable"

    # local data plane: this host's feed server + shard-aware drain; the
    # consistent-hash ring says which gids this host serves (the wire
    # actor_id is the LOCAL stream, exactly the supervisor's mapping)
    server = ReplayFeedServer(replay)
    fleet = STREAMS * nproc
    gids = local_slice(fleet, nproc, pid)
    stop = threading.Event()
    counter = [0] * STREAMS
    errs: list[str] = []
    writers = []
    for s in range(STREAMS):
        client = ReplayFeedClient("127.0.0.1", server.address[1], actor_id=s)
        th = threading.Thread(
            target=_writer, name=f"writer-{s}",
            args=(client, stop, target_tps / fleet, 5000 + gids[s],
                  counter, s, errs), daemon=True)
        th.start()
        writers.append(th)

    def dispatch() -> None:
        with server.replay_lock:
            solver.train_steps_device_per(replay, chain=CHAIN)
        # scheduling yield: on the synchronous-CPU fallback the dispatch
        # runs to completion INSIDE the lock hold (a real accelerator
        # dispatches async and releases in microseconds), so without a
        # gap the serve threads starve behind an always-held RLock. The
        # 10 ms mirrors the inter-dispatch host work a production loop
        # has anyway, and is charged to the measured wall time.
        time.sleep(0.01)

    def fence() -> None:
        jax.block_until_ready(solver.state.params)

    # warmup (compile) + calibration; the per-rep dispatch count must be
    # AGREED or hosts would desync their collective sequences
    for _ in range(2):
        dispatch()
    fence()
    t0 = time.perf_counter()
    for _ in range(2):
        dispatch()
    fence()
    per_dispatch = (time.perf_counter() - t0) / 2
    # floor of 3 dispatches per rep: averaging across dispatches is what
    # keeps the per-point spread under the 0.05 gate on a noisy 1-core
    # container (single-dispatch reps measured up to ~5% jitter, and the
    # paced RPC admissions land unevenly across short reps)
    k = int(min(max(round(2.0 / max(per_dispatch, 1e-6)), 3), 40))
    k = global_max_int(k)

    # settle window (discarded) re-anchors the achieved-ingest counter
    # past the writers' ramp — PR 9's fenced settled-window discipline.
    # k+2 dispatches: at the 4-host point one window is not enough to
    # flush scheduler warm-in, and a low first rep blows the spread gate
    for _ in range(k + 2):
        dispatch()
    fence()
    ingest_t0, ingest_c0 = time.perf_counter(), sum(counter)

    rates = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        for _ in range(k):
            dispatch()
        fence()
        rates.append(k * CHAIN / (time.perf_counter() - t0))
    ingest = ((sum(counter) - ingest_c0)
              / (time.perf_counter() - ingest_t0))

    stop.set()
    for th in writers:
        th.join(timeout=10.0)
    # ledger BEFORE close: every add this server ever saw, by actor id —
    # the zero-cross-host-RPC evidence (foreign ids would show up here)
    summary = server.telemetry_summary()
    seen = sorted(int(a) for a in server.last_seen)
    server.close()

    local_ids = list(range(STREAMS))
    out = {
        "pid": pid,
        "n_hosts": nproc,
        "rates": [round(r, 3) for r in rates],
        "dispatch_k": k,
        "ingest_t_per_s": round(ingest, 1),
        "assigned_gids": [int(g) for g in gids],
        "actor_ids_seen": seen,
        "rpc_add_calls": int(summary.get("rpc/add_transitions_calls", 0)),
        "foreign_actor_calls": sum(1 for a in seen if a not in local_ids),
        "shard_rows": int(summary.get("shard/rows", 0)),
        "writer_errors": errs[:2],
    }
    with open(out_path, "w") as fh:
        json.dump(out, fh)


if __name__ == "__main__":
    main()
