"""Diagnostic: CartPole learning curve under cartpole_config().

Runs training with periodic eval to find where/why the run lands at ~120
instead of >=475 (VERDICT weak #1). Not part of the package.
"""
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")
from distributed_deep_q_tpu.compat import set_cpu_device_count
set_cpu_device_count(8)

import numpy as np

from distributed_deep_q_tpu.config import cartpole_config
from distributed_deep_q_tpu.train import train_single_process, evaluate

cfg = cartpole_config()
cfg.mesh.backend = "cpu"
cfg.train.eval_every = 2_000
cfg.train.eval_episodes = 5

from distributed_deep_q_tpu.config import apply_overrides

apply_overrides(cfg, sys.argv[1:])
for arg in sys.argv[1:]:
    print(f"override {arg}")

import tempfile

from distributed_deep_q_tpu.metrics import Metrics

jsonl = tempfile.mktemp(suffix=".jsonl")
t0 = time.time()
out = train_single_process(cfg, metrics=Metrics(jsonl_path=jsonl),
                           log_every=2_000)
for line in open(jsonl):
    print(line.strip())
solver = out.pop("solver")
final = evaluate(solver, cfg, episodes=10)
print(f"\nwall={time.time()-t0:.0f}s final10={final:.1f} summary={ {k: v for k, v in out.items()} }")
