"""Decompose the bench MFU (VERDICT r3 next #4): where does the non-MXU
2/3 of the idle train step go?

``bench.py`` reports one MFU number (flops/step × steps/s ÷ peak) with no
attribution. This script splits the idle_uniform step into separately
jitted, separately timed component programs on the REAL chip, and pairs
each with XLA's own cost analysis (flops + bytes accessed) so every
component gets a roofline verdict — compute-bound (time ≈ flops/peak) or
HBM-bound (time ≈ bytes/bandwidth):

- ``fwd``        — one online-net forward (the pure-MXU lower bound)
- ``loss_grad``  — value_and_grad of the DQN loss: online fwd+bwd, target
                   fwd, Double-DQN selection fwd (≈5× fwd FLOPs)
- ``full_hostb`` — the complete train step (loss_grad + Adam + Polyak θ⁻)
                   on a pre-composed device batch (no ring gather)
- ``full_ring``  — the production step: ring gather/stack + full_hostb
                   (bench.py's idle_uniform program)

Deltas attribute wall time: gather = full_ring − full_hostb; optimizer +
target tail = full_hostb − loss_grad. A batch sweep (256→2048) shows how
MFU scales when the fixed per-step costs amortize. Results + analysis are
recorded in PERF.md.

Run on the TPU box:  python scripts/mfu_breakdown.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPS = 5
HBM_GBPS = {  # public per-chip HBM bandwidth, GB/s (keys match
    #           bench.PEAK_FLOPS — the flops side lives there)
    "TPU v6 lite": 1640.0, "TPU v5 lite": 819.0, "TPU v5": 2765.0,
    "TPU v4": 1228.0, "TPU v3": 900.0,
}


def lookup(table: dict, kind: str):
    for prefix, v in sorted(table.items(), key=lambda kv: -len(kv[0])):
        if kind.startswith(prefix):
            return v
    return None


def _fence(out):
    """TRUE device sync: D2H-read the smallest output leaf (data-depends
    on the whole call chain). ``block_until_ready`` is NOT a fence on the
    tunneled runtime — it acks enqueue (50 chained 8192³ bf16 matmuls
    "ready" in 1.6 ms ≈ 34 PF/s, impossible); see bench.py docstring."""
    import jax

    leaf = min(jax.tree_util.tree_leaves(out), key=lambda x: x.size)
    return np.asarray(jax.device_get(leaf))


def time_program(fn, args, iters: int, donate_state: bool = False):
    """Median seconds/call of a compiled program, fenced by D2H readback;
    the separately measured fence RTT is subtracted from each rep.
    ``donate_state`` reuses the returned state as the next call's first
    arg (train-step style)."""
    import jax
    import jax.numpy as jnp

    out = fn(*args)
    _fence(out)
    if donate_state:
        args = (out[0],) + args[1:]
    # RTT = median first read of FRESH drained buffers (a re-read of a
    # fetched array hits jax's host-side cache and measures ~0.1 ms, not
    # the tunnel round trip; median of 3 — one jittery round trip must
    # not skew every rep's subtraction)
    leaf = min(jax.tree_util.tree_leaves(out), key=lambda x: x.size)
    rtts = []
    for k in range(3):
        fresh = jnp.asarray(leaf) + k
        time.sleep(0.25)
        t0 = time.perf_counter()
        np.asarray(jax.device_get(fresh))
        rtts.append(time.perf_counter() - t0)
    rtt = float(np.median(rtts))
    rates = []
    for _ in range(REPS):
        a = args
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*a)
            if donate_state:
                a = (out[0],) + a[1:]
        _fence(out)
        rates.append(max(time.perf_counter() - t0 - rtt, 1e-9) / iters)
        if donate_state:
            args = (out[0],) + args[1:]
    return float(np.median(rates)), args


def cost_of(lowered) -> dict:
    """flops + bytes-accessed from XLA's compiled cost model."""
    try:
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return {"flops": float(cost.get("flops", 0.0)),
                "bytes": float(cost.get("bytes accessed", 0.0))}
    except Exception:
        return {"flops": 0.0, "bytes": 0.0}


def build(batch: int, capacity: int = 65_536):
    import jax

    from bench import build as bench_build
    from distributed_deep_q_tpu import config as cfg_mod

    solver, replay = bench_build(
        cfg_mod, capacity=capacity, batch=batch, prioritized=False,
        pallas=False, prefill=min(40_000, capacity // 2) if
        jax.devices()[0].platform != "cpu" else 8192)
    return solver, replay


def main() -> None:
    import os

    import jax

    if os.environ.get("DDQ_PLATFORM") == "cpu":
        # the container's sitecustomize pre-imports jax pinned to the TPU
        # platform; env JAX_PLATFORMS=cpu is too late — override via config
        jax.config.update("jax_platforms", "cpu")
        from distributed_deep_q_tpu.compat import set_cpu_device_count
        set_cpu_device_count(8)
    import jax.numpy as jnp

    from bench import peak_flops_for

    on_cpu = jax.devices()[0].platform == "cpu"
    iters = 20 if on_cpu else 400
    out: dict = {"device_kind": getattr(jax.devices()[0], "device_kind",
                                        jax.devices()[0].platform)}
    peak = peak_flops_for(jax.devices()[0])
    hbm = lookup(HBM_GBPS, out["device_kind"])

    solver, replay = build(512)
    learner = solver.learner
    state = solver.state
    batch = replay.sample(512)
    batch.pop("_sampled_at", None)
    clean = {k: np.asarray(v) for k, v in batch.items() if k != "index"}

    # -- full_ring: the production idle program ---------------------------
    ring_fn = None
    fs = tuple(solver.config.net.frame_shape)
    if fs not in learner._ring_steps:
        solver.train_step_from_ring(replay.ring, dict(batch))
        state = solver.state
    ring_fn = learner._ring_steps[fs]
    t_ring, (state, *_) = time_program(
        ring_fn, (state, replay.ring, clean), iters, donate_state=True)
    out["full_ring_ms"] = round(1e3 * t_ring, 4)
    out["full_ring_cost"] = cost_of(
        ring_fn.lower(state, replay.ring, clean))

    # -- full_hostb: same step, batch pre-composed on device --------------
    from distributed_deep_q_tpu.replay.device_ring import compose_stacks
    from jax.sharding import NamedSharding, PartitionSpec as P
    from distributed_deep_q_tpu.compat import shard_map
    from distributed_deep_q_tpu.parallel.mesh import AXIS_DP

    compose = jax.jit(shard_map(
        lambda ring, oidx, valid: compose_stacks(ring, oidx, valid, fs),
        mesh=learner.mesh, in_specs=(P(AXIS_DP), P(AXIS_DP), P(AXIS_DP)),
        out_specs=P(AXIS_DP), check_vma=False))
    composed = {
        "obs": compose(replay.ring, clean["oidx"], clean["valid"]),
        "next_obs": compose(replay.ring, clean["noidx"], clean["nvalid"]),
        "action": jnp.asarray(clean["action"]),
        "reward": jnp.asarray(clean["reward"]),
        "discount": jnp.asarray(clean["discount"]),
        "weight": jnp.asarray(clean["weight"]),
    }
    composed = {k: jax.device_put(v, NamedSharding(learner.mesh, P(AXIS_DP)))
                for k, v in composed.items()}
    full_fn = learner._train_step
    t_hostb, (state, *_) = time_program(
        full_fn, (state, composed), iters, donate_state=True)
    out["full_hostb_ms"] = round(1e3 * t_hostb, 4)
    out["full_hostb_cost"] = cost_of(full_fn.lower(state, composed))

    # -- loss_grad: fwd+bwd only (no optimizer, no θ⁻ refresh) ------------
    cfg = solver.config.train
    from distributed_deep_q_tpu.ops.losses import bellman_targets, dqn_loss

    def loss_fn(params, target_params, b):
        q = solver.apply_fn(params, b["obs"])
        q_next_t = solver.apply_fn(target_params, b["next_obs"])
        q_next_o = jax.lax.stop_gradient(
            solver.apply_fn(params, b["next_obs"]))
        targets = bellman_targets(b["reward"], b["discount"], q_next_t,
                                  q_next_o, True)
        loss, _ = dqn_loss(q, b["action"], targets, b["weight"],
                           cfg.huber_delta)
        return loss

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    t_grad, _ = time_program(
        grad_fn, (state.params, state.target_params, composed), iters)
    out["loss_grad_ms"] = round(1e3 * t_grad, 4)
    out["loss_grad_cost"] = cost_of(
        grad_fn.lower(state.params, state.target_params, composed))

    # -- fwd: one online forward ------------------------------------------
    fwd_fn = jax.jit(solver.apply_fn)
    t_fwd, _ = time_program(fwd_fn, (state.params, composed["obs"]), iters)
    out["fwd_ms"] = round(1e3 * t_fwd, 4)
    out["fwd_cost"] = cost_of(fwd_fn.lower(state.params, composed["obs"]))

    # -- dispatch floor: tiny program, same tunnel ------------------------
    tiny = jnp.zeros(8, jnp.float32)
    tiny_fn = jax.jit(lambda x: x + 1.0)
    t_disp, _ = time_program(tiny_fn, (tiny,), iters)
    out["dispatch_floor_ms"] = round(1e3 * t_disp, 4)

    # -- attribution + rooflines ------------------------------------------
    out["gather_ms"] = round(out["full_ring_ms"] - out["full_hostb_ms"], 4)
    out["opt_tail_ms"] = round(out["full_hostb_ms"] - out["loss_grad_ms"], 4)
    if peak and hbm:
        for key in ("full_ring", "full_hostb", "loss_grad", "fwd"):
            c = out[f"{key}_cost"]
            out[f"{key}_roofline_ms"] = {
                "compute": round(1e3 * c["flops"] / peak, 4),
                "hbm": round(1e3 * c["bytes"] / (hbm * 1e9), 4),
            }
        out["mfu_full_ring"] = round(
            out["full_ring_cost"]["flops"] / peak / t_ring, 4)

    n_params = sum(x.size for x in jax.tree_util.tree_leaves(state.params))
    out["param_count"] = int(n_params)
    del solver, replay, state, composed, clean

    # -- batch sweep: does MFU climb as fixed costs amortize? -------------
    sweep = {}
    for b in ((256,) if on_cpu else (256, 1024, 2048)):
        s, r = build(b)
        bt = r.sample(b)
        bt.pop("_sampled_at", None)
        bt = {k: np.asarray(v) for k, v in bt.items() if k != "index"}
        s.train_step_from_ring(r.ring, dict(bt))
        fn = s.learner._ring_steps[fs]
        t, _ = time_program(fn, (s.state, r.ring, bt), max(iters // 2, 5),
                            donate_state=True)
        c = cost_of(fn.lower(s.state, r.ring, bt))
        sweep[b] = {"ms": round(1e3 * t, 4),
                    "steps_per_s": round(1.0 / t, 1),
                    "mfu": round(c["flops"] / peak / t, 4) if peak else None}
        del s, r
    out["batch_sweep"] = sweep

    print(json.dumps(out))


if __name__ == "__main__":
    main()
