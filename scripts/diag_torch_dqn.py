"""Diagnostic-only: minimal torch DQN replicating the SB3-zoo CartPole-v1
recipe as faithfully as possible, to establish whether that recipe solves in
THIS container (gymnasium version, CPU) at all. Not part of the package.
"""
import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import gymnasium

SEED = 0
TOTAL = 50_000
LR = 2.3e-3
BATCH = 64
BUF = 100_000
LEARN_START = 1_000
GAMMA = 0.99
TRAIN_FREQ = 256
GRAD_STEPS = 128
TGT_INTERVAL = 10       # env steps, SB3 semantics
EPS_FRACTION = 0.16
EPS_FINAL = 0.04

torch.manual_seed(SEED)
rng = np.random.default_rng(SEED)
env = gymnasium.make("CartPole-v1")


def make_net():
    return nn.Sequential(nn.Linear(4, 256), nn.ReLU(),
                         nn.Linear(256, 256), nn.ReLU(), nn.Linear(256, 2))


q, q_tgt = make_net(), make_net()
q_tgt.load_state_dict(q.state_dict())
opt = torch.optim.Adam(q.parameters(), lr=LR)

obs_buf = np.zeros((BUF, 4), np.float32)
nobs_buf = np.zeros((BUF, 4), np.float32)
act_buf = np.zeros(BUF, np.int64)
rew_buf = np.zeros(BUF, np.float32)
done_buf = np.zeros(BUF, np.float32)
cursor, size = 0, 0


def add(o, a, r, no, d):
    global cursor, size
    obs_buf[cursor], act_buf[cursor], rew_buf[cursor] = o, a, r
    nobs_buf[cursor], done_buf[cursor] = no, d
    cursor = (cursor + 1) % BUF
    size = min(size + 1, BUF)


def train_burst():
    for _ in range(GRAD_STEPS):
        idx = rng.integers(0, size, BATCH)
        o = torch.as_tensor(obs_buf[idx])
        no = torch.as_tensor(nobs_buf[idx])
        a = torch.as_tensor(act_buf[idx])
        r = torch.as_tensor(rew_buf[idx])
        d = torch.as_tensor(done_buf[idx])
        with torch.no_grad():
            tgt = r + (1 - d) * GAMMA * q_tgt(no).max(1).values
        qsa = q(o).gather(1, a[:, None])[:, 0]
        loss = F.smooth_l1_loss(qsa, tgt)
        opt.zero_grad()
        loss.backward()
        nn.utils.clip_grad_norm_(q.parameters(), 10.0)
        opt.step()


def evaluate(episodes=5):
    e = gymnasium.make("CartPole-v1")
    rets = []
    for ep in range(episodes):
        o, _ = e.reset(seed=10_000 + ep)
        ret, over = 0.0, False
        while not over:
            with torch.no_grad():
                a = int(q(torch.as_tensor(o[None])).argmax())
            o, r, term, trunc, _ = e.step(a)
            ret += r
            over = term or trunc
        rets.append(ret)
    return float(np.mean(rets))


o, _ = env.reset(seed=SEED)
ep = 0
for t in range(1, TOTAL + 1):
    frac = min(t / (EPS_FRACTION * TOTAL), 1.0)
    eps = 1.0 + frac * (EPS_FINAL - 1.0)
    if rng.random() < eps:
        a = int(rng.integers(2))
    else:
        with torch.no_grad():
            a = int(q(torch.as_tensor(o[None])).argmax())
    no, r, term, trunc, _ = env.step(a)
    add(o, a, r, no, float(term))  # truncation bootstraps (d=0)
    o = no
    if term or trunc:
        o, _ = env.reset(seed=SEED + 1 + ep)
        ep += 1
    if t % TGT_INTERVAL == 0:
        q_tgt.load_state_dict(q.state_dict())
    if t >= LEARN_START and t % TRAIN_FREQ == 0:
        train_burst()
    if t % 2_000 == 0:
        print(f"t={t} eval={evaluate():.1f}", flush=True)
print("final10:", evaluate(10))
