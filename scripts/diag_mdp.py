"""Numerics sanity: solver must recover analytic Q* on a tiny known MDP.

2-state MDP, one-hot obs. State 0: action 0 -> stay s0 r=0; action 1 -> s1
r=1. State 1: any action -> terminal r=0 ... make it simple:

Chain: s0 -a1-> s1 (r=1), s1 -a1-> terminal (r=1); a0 stays with r=0.
gamma=0.9.
Q*(s1,a1)=1, Q*(s1,a0)=0.9*V(s1)=0.9*1=0.9? V(s1)=max(Q)=1 => Q*(s1,a0)=0+0.9*1=0.9
Q*(s0,a1)=1+0.9*V(s1)=1.9 ; Q*(s0,a0)=0+0.9*V(s0)=0.9*1.9=1.71
"""
import jax

jax.config.update("jax_platforms", "cpu")
from distributed_deep_q_tpu.compat import set_cpu_device_count
set_cpu_device_count(8)

import numpy as np

from distributed_deep_q_tpu.config import Config
from distributed_deep_q_tpu.replay.replay_memory import ReplayMemory
from distributed_deep_q_tpu.solver import Solver

cfg = Config()
cfg.mesh.backend = "cpu"
cfg.net.kind = "mlp"
cfg.net.num_actions = 2
cfg.net.hidden = (64, 64)
cfg.train.lr = 1e-3
cfg.train.gamma = 0.9
cfg.train.target_update_period = 100

solver = Solver(cfg, obs_dim=2)
replay = ReplayMemory(1024, (2,), np.float32, seed=0)

s0 = np.array([1, 0], np.float32)
s1 = np.array([0, 1], np.float32)
g = 0.9
# transitions: (obs, a, r, next_obs, discount)
replay.add(s0, 0, 0.0, s0, g)
replay.add(s0, 1, 1.0, s1, g)
replay.add(s1, 0, 0.0, s1, g)
replay.add(s1, 1, 1.0, s1, 0.0)  # terminal

for i in range(4000):
    solver.train_step(replay.sample(64))

q0, q1 = solver.q_values(s0)[0], solver.q_values(s1)[0]
print("Q(s0):", q0, "expected [1.71, 1.9]")
print("Q(s1):", q1, "expected [0.9, 1.0]")
ok = (np.allclose(q0, [1.71, 1.9], atol=0.05)
      and np.allclose(q1, [0.9, 1.0], atol=0.05))
print("NUMERICS", "OK" if ok else "BROKEN")
