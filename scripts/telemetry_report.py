#!/usr/bin/env python
"""Human-readable report over a run's metrics JSONL (observability spine).

Reads the JSONL a ``Metrics(jsonl_path=...)`` run wrote and prints:

- run overview — record/step span, wall time, throughput counters;
- training curve tail — loss / q_mean / return at the end of the run;
- learning dynamics — the ``learn/*`` gauges the on-device metrics
  plane accumulated inside the fused-chain / Anakin scan bodies
  (loss, grad norm pre/post clip, Q scale, PER priority and IS-weight
  statistics) plus the TD-|error| histogram percentiles; under
  ``--strict`` any learn divergence finding in a fleet verdict fails
  the gate even if the run later recovered;
- per-phase step breakdown — ``time_<phase>_ms`` means plus the
  streaming-histogram p50/p99 where the run recorded them;
- RPC server table — per-method call counts, latency percentiles and
  payload sizes (``rpc/<method>_*`` keys from the ``stats`` RPC /
  ``telemetry_summary``);
- fleet counters — θ-pull, heartbeat RTT, env-step latency histograms
  the actors flushed back (``fleet/*``);
- queue gauges — replay/staged-row depths and params-version lag
  (``queue/*``), the r5 host-OOM early-warning signals;
- tracing & data age — span-drop / clock-skew counters (``trace/*``)
  and the ingest-lag histogram; ``learner/time_to_learn_ms`` rides the
  learner table. Runs that never enabled tracing emit none of these
  keys and the sections simply don't print;
- health & SLO plane — monitor/aggregator self-gauges, live efficiency
  gauges (``train/steps_per_s``, ``train/mfu``,
  ``train/ingest_utilization``), and the aggregated fleet verdict the
  supervisor logged under ``health/verdict`` — final status, how many
  records spent degraded/critical, and the last verdict's findings;
- anomalies — bad JSON, non-monotonic steps, logging gaps, stalled
  counters, non-finite values, span-ring overflow.

``--strict`` exits non-zero when anomalies or SLO violations are
present (same convention as ``scripts/trace_report.py``): any record
with a CRITICAL fleet verdict, a run that ENDS degraded/critical, or
any structural anomaly fails the report. Transient degraded windows
that recover are reported but pass — that is the health plane working.

Pure stdlib (json/math/argparse): usable on any host with the JSONL file,
no jax/numpy required. ``load_records`` / ``validate_records`` /
``slo_problems`` are importable by tests and other tooling.
"""

from __future__ import annotations

import argparse
import json
import math
import sys

# suffixes Histogram.summary() emits, in display order
HIST_SUFFIXES = ("count", "mean", "p50", "p95", "p99", "max")


def load_records(path: str) -> list[dict]:
    """Parse one JSONL file; raises ValueError naming the bad line."""
    records = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: invalid JSON ({e})")
            if not isinstance(rec, dict):
                raise ValueError(f"{path}:{lineno}: record is not an object")
            records.append(rec)
    return records


def validate_records(records: list[dict]) -> list[str]:
    """Structural problems: missing/non-monotonic ``step``, non-finite
    values. Returns human-readable problem strings (empty = clean)."""
    problems = []
    last_step = None
    for i, rec in enumerate(records):
        if "step" not in rec:
            problems.append(f"record {i}: missing 'step'")
            continue
        step = rec["step"]
        if not isinstance(step, (int, float)):
            problems.append(f"record {i}: non-numeric step {step!r}")
            continue
        if last_step is not None and step < last_step:
            problems.append(
                f"record {i}: step {step} < previous {last_step} "
                "(non-monotonic)")
        last_step = step
        for k, v in rec.items():
            if isinstance(v, float) and not math.isfinite(v):
                problems.append(f"record {i} (step {step}): {k} = {v}")
    return problems


def _series(records: list[dict], key: str) -> list:
    return [r[key] for r in records if key in r]


def _verdicts(records: list[dict]) -> list[dict]:
    """The aggregated fleet verdicts a supervisor run logged — the one
    non-scalar value on the metrics spine (Metrics.log passes dicts
    through to JSONL; the TB mirror skips them)."""
    return [v for v in _series(records, "health/verdict")
            if isinstance(v, dict)]


def slo_problems(records: list[dict]) -> list[str]:
    """SLO violations ``--strict`` gates on: a CRITICAL fleet verdict in
    ANY record, or a run whose FINAL verdict is not ok. Returns
    human-readable problem strings naming the violated rules."""
    verdicts = _verdicts(records)
    if not verdicts:
        return []
    out = []
    crit = [i for i, v in enumerate(verdicts)
            if v.get("status") == "critical"]
    if crit:
        out.append(f"SLO: fleet verdict CRITICAL in {len(crit)} "
                   f"record(s) (first at verdict {crit[0]})")
    final = verdicts[-1]
    if final.get("status") not in (None, "ok"):
        rules = sorted({str(f.get("rule", "?"))
                        for f in final.get("findings") or []
                        if isinstance(f, dict)})
        out.append(f"SLO: run ended {final.get('status')}"
                   + (f" ({', '.join(rules)})" if rules else ""))
    return out


# findings the learning-dynamics monitor emits (health.default_learn_
# rules/trends over the learn/* plane). ``--strict`` treats ANY such
# finding as a failure even if the fleet later recovered: a loss that
# diverged and came back still trained on poisoned updates, so the run
# is not a clean gate.
LEARN_DIVERGENCE_RULES = (
    "loss_divergence", "loss_collapse", "grad_norm_spike",
    "q_overestimation", "priority_collapse", "loss_nonfinite")


def learn_problems(records: list[dict]) -> list[str]:
    """Learning-dynamics failures ``--strict`` gates on: any fleet
    verdict carrying a learn divergence finding, or a run whose last
    window still counted non-finite losses."""
    hits: dict[str, int] = {}
    for v in _verdicts(records):
        for f in v.get("findings") or []:
            if isinstance(f, dict) \
                    and str(f.get("rule")) in LEARN_DIVERGENCE_RULES:
                r = str(f.get("rule"))
                hits[r] = hits.get(r, 0) + 1
    out = [f"learning: divergence finding '{rule}' in {n} verdict(s)"
           for rule, n in sorted(hits.items())]
    nf = [v for v in _series(records, "learn/loss_nonfinite")
          if isinstance(v, (int, float))]
    if nf and nf[-1] > 0:
        out.append(f"learning: {int(nf[-1])} non-finite loss step(s) in "
                   "the final window")
    return out


def elastic_problems(records: list[dict]) -> list[str]:
    """Elastic-fleet failures ``--strict`` gates on (ISSUE 17): a shard
    handoff that lost rows, or an autoscaler decision that fired
    without a named finding — every decision must carry the rule and
    the burn numbers that triggered it (lineage-traceable), else the
    capacity change is an unauditable mutation of a production fleet."""
    out = []
    lost = [v for v in _series(records, "fleet/handoff_lost_rows")
            if isinstance(v, (int, float))]
    if any(v > 0 for v in lost):
        out.append(f"elastic: shard handoff lost {int(max(lost))} "
                   "row(s) — the manifest-committed export/import "
                   "round trip must be lossless")
    for i, rec in enumerate(records):
        decisions = rec.get("autoscale/decision")
        if decisions is None:
            continue
        if isinstance(decisions, dict):
            decisions = [decisions]
        if not isinstance(decisions, list):
            out.append(f"elastic: record {i}: autoscale/decision is "
                       f"{type(decisions).__name__}, not a list")
            continue
        for d in decisions:
            if not isinstance(d, dict) or not d.get("rule"):
                out.append(f"elastic: record {i}: autoscaler decision "
                           "without a named rule")
            elif not all(isinstance(d.get(k), (int, float))
                         for k in ("burn_fast", "burn_slow")):
                out.append(f"elastic: record {i}: decision "
                           f"'{d.get('rule')}' missing burn numbers")
    # executor lineage (ISSUE 20): every APPLIED scale action must name
    # the decision rule it executed — a process start/stop with no
    # provenance is exactly the unauditable mutation the decision JSONL
    # exists to prevent
    for i, rec in enumerate(records):
        applied = rec.get("autoscale/applied")
        if applied is None:
            continue
        if isinstance(applied, dict):
            applied = [applied]
        if not isinstance(applied, list):
            out.append(f"elastic: record {i}: autoscale/applied is "
                       f"{type(applied).__name__}, not a list")
            continue
        for a in applied:
            if not isinstance(a, dict) or not a.get("rule"):
                out.append(f"elastic: record {i}: applied scale action "
                           "without a named decision rule")
            elif not a.get("action"):
                out.append(f"elastic: record {i}: applied entry for rule "
                           f"'{a.get('rule')}' names no action")
    # applied vs target (ISSUE 20): with the executor on, the LAST
    # record's fleet size must have converged to the scaler's target —
    # a sustained mismatch means the control loop is open after all
    applied_g = [v for v in _series(records, "autoscale/applied_actors")
                 if isinstance(v, (int, float))]
    target_g = [v for v in _series(records, "autoscale/target_actors")
                if isinstance(v, (int, float))]
    if applied_g and target_g and applied_g[-1] != target_g[-1]:
        out.append(f"elastic: final autoscale/applied_actors "
                   f"{int(applied_g[-1])} != autoscale/target_actors "
                   f"{int(target_g[-1])} — executor did not converge "
                   "on the scaler's target")
    return out


def _hist_groups(records: list[dict], prefix: str) -> dict[str, dict]:
    """Latest value per histogram-summary group under ``prefix``:
    ``{'fleet/param_pull_ms': {'count': ..., 'p50': ..., ...}, ...}``."""
    groups: dict[str, dict] = {}
    for rec in records:
        for k, v in rec.items():
            if not k.startswith(prefix):
                continue
            for suf in HIST_SUFFIXES:
                if k.endswith(f"_{suf}"):
                    groups.setdefault(k[: -len(suf) - 1], {})[suf] = v
                    break
    return groups


def _fmt(v, width: int = 9) -> str:
    if v is None:
        return " " * (width - 1) + "-"
    if isinstance(v, float) and not math.isfinite(v):
        return f"{v!s:>{width}}"
    if isinstance(v, float) and abs(v) < 1e5:
        return f"{v:>{width}.2f}"
    return f"{int(v):>{width}d}"


def _table(title: str, rows: list[tuple], header: tuple,
           out: list[str]) -> None:
    if not rows:
        return
    out.append(f"\n== {title} ==")
    name_w = max(len(str(r[0])) for r in rows + [header])
    out.append("  " + str(header[0]).ljust(name_w)
               + "".join(f"{h:>10}" for h in header[1:]))
    for r in rows:
        out.append("  " + str(r[0]).ljust(name_w)
                   + "".join(" " + _fmt(v) for v in r[1:]))


def _gap_anomalies(records: list[dict], factor: float = 5.0) -> list[str]:
    """Logging gaps (wall-time deltas >> the median cadence) and stalled
    throughput counters."""
    out = []
    ts = [r["t"] for r in records if isinstance(r.get("t"), (int, float))]
    if len(ts) >= 4:
        deltas = [b - a for a, b in zip(ts, ts[1:])]
        med = sorted(deltas)[len(deltas) // 2]
        if med > 0:
            for i, d in enumerate(deltas):
                if d > factor * med:
                    out.append(
                        f"logging gap: {d:.1f}s between records {i} and "
                        f"{i + 1} (median cadence {med:.1f}s)")
    for key in ("env_steps", "grad_steps_per_s"):
        vals = _series(records, key)
        if len(vals) >= 3 and vals[-1] == vals[-2] == vals[-3] \
                and (key != "env_steps" or vals[-1] == vals[0]):
            out.append(f"counter stalled: {key} flat at {vals[-1]} over the "
                       "last 3 records")
    return out


def render_report(records: list[dict], last: int = 0) -> str:
    if last:
        records = records[-last:]
    if not records:
        return "no records"
    out: list[str] = []
    steps = _series(records, "step")
    ts = _series(records, "t")
    out.append("== run overview ==")
    out.append(f"  records             {len(records)}")
    if steps:
        out.append(f"  step span           {steps[0]} .. {steps[-1]}")
    if ts:
        out.append(f"  wall span           {ts[-1] - ts[0]:.1f}s "
                   f"(t={ts[0]:.1f} .. {ts[-1]:.1f})")
    for key in ("grad_steps_per_s", "env_steps_per_s", "env_steps",
                "replay_size", "actor_restarts"):
        vals = [v for v in _series(records, key)
                if isinstance(v, (int, float))]
        if vals:
            out.append(f"  {key:<19} last {_fmt(vals[-1]).strip()}   "
                       f"max {_fmt(max(vals)).strip()}")

    rows = []
    for key in ("loss", "q_mean", "return_avg100", "eval_return", "epsilon"):
        vals = [v for v in _series(records, key)
                if isinstance(v, (int, float)) and math.isfinite(v)]
        if vals:
            rows.append((key, vals[0], vals[-1], min(vals), max(vals)))
    _table("training curve", rows, ("metric", "first", "last", "min", "max"),
           out)

    # learning dynamics: the learn/* gauges the on-device metrics plane
    # accumulated inside the fused-chain / Anakin scan bodies
    # (learning.py), plus the cumulative TD-|error| histogram summary.
    # Runs without cfg.train.learn_metrics log none of these keys.
    rows = []
    for key in ("learn/loss", "learn/grad_norm", "learn/grad_norm_clipped",
                "learn/q_mean", "learn/q_max", "learn/td_mean",
                "learn/td_max", "learn/prio_mean", "learn/prio_max",
                "learn/is_weight_mean", "learn/is_weight_min",
                "learn/target_refreshes", "learn/loss_nonfinite",
                "learn/steps"):
        vals = [v for v in _series(records, key)
                if isinstance(v, (int, float)) and math.isfinite(v)]
        if vals:
            rows.append((key[6:], vals[0], vals[-1], min(vals), max(vals)))
    _table("learning dynamics (learn/*)", rows,
           ("gauge", "first", "last", "min", "max"), out)
    rows = [(name[6:], d.get("count"), d.get("p50"), d.get("p95"),
             d.get("p99"), d.get("max"))
            for name, d in sorted(
                _hist_groups(records, "learn/td_error").items())]
    _table("TD |error| (sampled-priority distribution)", rows,
           ("histogram", "count", "p50", "p95", "p99", "max"), out)

    # per-phase step breakdown: time_<phase>_ms (+ _p50_ms/_p99_ms)
    phases: dict[str, dict] = {}
    for rec in records:
        for k, v in rec.items():
            if not (k.startswith("time_") and k.endswith("_ms")):
                continue
            stem = k[5:-3].rstrip("_")  # 'sample', 'sample_p50', ...
            for suf in ("p50", "p99"):
                if stem.endswith(f"_{suf}"):
                    phases.setdefault(stem[: -len(suf) - 1], {})[suf] = v
                    break
            else:
                phases.setdefault(stem, {})["mean"] = v
    rows = [(name, d.get("mean"), d.get("p50"), d.get("p99"))
            for name, d in sorted(phases.items())]
    _table("step phases (ms, latest window)", rows,
           ("phase", "mean", "p50", "p99"), out)

    # RPC server table — join the latency/bytes/calls keys per method
    lat = _hist_groups(records, "rpc/")
    methods: dict[str, dict] = {}
    calls: dict[str, float] = {}
    for rec in records:
        for k, v in rec.items():
            if k.startswith("rpc/") and k.endswith("_calls"):
                calls[k[4:-6]] = v
    for group, d in lat.items():
        name = group[4:]
        if name.endswith("_ms"):
            methods.setdefault(name[:-3], {})["ms"] = d
        elif name.endswith("_bytes"):
            methods.setdefault(name[:-6], {})["bytes"] = d
    rows = []
    for m in sorted(set(methods) | set(calls)):
        ms = methods.get(m, {}).get("ms", {})
        by = methods.get(m, {}).get("bytes", {})
        rows.append((m, calls.get(m), ms.get("p50"), ms.get("p95"),
                     ms.get("p99"), ms.get("max"), by.get("p95")))
    _table("rpc methods", rows, ("method", "calls", "ms_p50", "ms_p95",
                                 "ms_p99", "ms_max", "B_p95"), out)

    rows = [(name[6:], d.get("count"), d.get("p50"), d.get("p95"),
             d.get("p99"), d.get("max"))
            for name, d in sorted(_hist_groups(records, "fleet/").items())]
    _table("fleet (actor-side, ms)", rows,
           ("counter", "count", "p50", "p95", "p99", "max"), out)

    rows = [(name[8:], d.get("count"), d.get("p50"), d.get("p99"),
             d.get("max"))
            for name, d in sorted(_hist_groups(records, "learner/").items())]
    _table("learner (ms)", rows, ("counter", "count", "p50", "p99", "max"),
           out)

    rows = []
    for key in sorted({k for r in records for k in r
                       if k.startswith("queue/") or k == "fleet/actors_seen"}):
        vals = [v for v in _series(records, key)
                if isinstance(v, (int, float))]
        if vals:
            rows.append((key, vals[-1], min(vals), max(vals)))
    _table("queue gauges", rows, ("gauge", "last", "min", "max"), out)

    # durability plane: snapshot cadence/stall/size, generation retention,
    # quarantines, and wire CRC rejections (any nonzero quarantine or
    # checksum count deserves a look — it means damage was absorbed)
    rows = []
    for key in sorted({k for r in records for k in r
                       if k.startswith("durability/")
                       or k == "rpc/checksum_errors"}):
        vals = [v for v in _series(records, key)
                if isinstance(v, (int, float))]
        if vals:
            rows.append((key, vals[-1], min(vals), max(vals)))
    _table("durability (snapshots & integrity)", rows,
           ("gauge", "last", "min", "max"), out)

    # tracing plane: tracer counters + flush-level data-age histogram.
    # A run that never enabled tracing logs none of these keys, so both
    # row lists stay empty and _table skips the sections cleanly.
    rows = []
    for key in ("trace/spans_dropped", "trace/spans_buffered",
                "trace/clock_skew_ms", "trace/skew_samples"):
        vals = [v for v in _series(records, key)
                if isinstance(v, (int, float))]
        if vals:
            rows.append((key, vals[-1], min(vals), max(vals)))
    _table("tracing (spans & clock skew)", rows,
           ("gauge", "last", "min", "max"), out)
    rows = [(name[6:], d.get("count"), d.get("p50"), d.get("p95"),
             d.get("p99"), d.get("max"))
            for name, d in sorted(_hist_groups(records, "trace/").items())]
    _table("data age (ms)", rows,
           ("histogram", "count", "p50", "p95", "p99", "max"), out)

    # health & SLO plane: self-gauges + live efficiency, then the fleet
    # verdict trail. Runs without health enabled log none of these keys.
    rows = []
    for key in ("health/members", "health/findings", "health/degraded",
                "health/critical", "health/scrape_errors",
                "train/steps_per_s", "train/mfu",
                "train/ingest_utilization"):
        vals = [v for v in _series(records, key)
                if isinstance(v, (int, float))]
        if vals:
            rows.append((key, vals[-1], min(vals), max(vals)))
    _table("health & efficiency", rows, ("gauge", "last", "min", "max"),
           out)
    verdicts = _verdicts(records)
    if verdicts:
        final = verdicts[-1]
        n_deg = sum(v.get("status") == "degraded" for v in verdicts)
        n_crit = sum(v.get("status") == "critical" for v in verdicts)
        out.append("\n== fleet verdict ==")
        out.append(f"  final status        {final.get('status', '?')}")
        out.append(f"  degraded records    {n_deg}/{len(verdicts)}")
        out.append(f"  critical records    {n_crit}/{len(verdicts)}")
        for f in (final.get("findings") or [])[:10]:
            if isinstance(f, dict):
                out.append(
                    f"  ! [{f.get('severity', '?')}] "
                    f"{f.get('member') or '-'}: {f.get('rule', '?')} "
                    f"on {f.get('key', '?')}")

    problems = (validate_records(records) + _gap_anomalies(records)
                + slo_problems(records) + learn_problems(records)
                + elastic_problems(records))
    drops = [v for v in _series(records, "trace/spans_dropped")
             if isinstance(v, (int, float))]
    if drops and drops[-1] > 0:
        problems.append(
            f"tracing: {int(drops[-1])} spans dropped (ring overflow) — "
            "raise trace.buffer_spans or lower trace.sample_rate")
    out.append(f"\n== anomalies ({len(problems)}) ==")
    for p in problems[:50]:
        out.append(f"  ! {p}")
    if len(problems) > 50:
        out.append(f"  ... and {len(problems) - 50} more")
    if not problems:
        out.append("  none")
    return "\n".join(out)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", help="metrics JSONL file written by a run")
    ap.add_argument("--last", type=int, default=0,
                    help="only the last N records (default: all)")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero on anomalies or SLO violations")
    args = ap.parse_args(argv)
    try:
        records = load_records(args.jsonl)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(render_report(records, last=args.last))
    if args.strict:
        window = records[-args.last:] if args.last else records
        problems = (validate_records(window) + _gap_anomalies(window)
                    + slo_problems(window) + learn_problems(window)
                    + elastic_problems(window))
        if problems:
            print(f"strict: FAILED ({len(problems)} problem(s), first: "
                  f"{problems[0]})", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
