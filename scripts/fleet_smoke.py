"""Fleet-scale smoke harness (BASELINE config 4 evidence, VERDICT r2 #5).

Measures the learner host's capacity to serve an Ape-X-scale actor fleet:
N actor THREADS (one real socket connection each — the production wire
protocol, ``rpc/protocol.py``) stream n-step transition chunks into a
``ReplayFeedServer`` and pull θ periodically, while the learner loop
samples and steps under the production ``replay_lock`` discipline.

Thread actors, not processes: the RPC boundary (sockets + serialization +
server lock) is what scales with fleet size and is exactly what this
measures; env simulation cost is per-actor-host and irrelevant to the
learner-side question "does ingest at fleet scale starve the learner?".
On a 1-core container 64 OS processes would measure only timeshare
thrash; on a many-core actor host run ``actor_main`` processes instead
(actors/supervisor.py) — same protocol, same server path.

Phases: (A) fill/burst — actors stream UNTHROTTLED, measuring the server's
raw ingest capacity; (B) idle learner — actors paused, solo grad-step
rate; (C) concurrent — actors PACED to a realistic per-actor env rate
(flooding writers on a shared box measure GIL starvation, not the
production regime where each actor emits at env speed) + learner
together. Reported: burst ingest capacity, paced achieved ingest, idle vs
concurrent grad-steps/s (the contention ratio VERDICT r2 Weak #2 asked to
measure), θ-pull MB/s, distinct streams seen, per-thread errors.

Run: ``python scripts/fleet_smoke.py [num_actors] [vector|pixel]`` → one
JSON line (``pixel`` = frame streams into the fused device-PER replay).

NOTE: ``run_pixel_fleet_smoke`` intentionally mirrors (rather than
parameterizes) this harness's phase scaffolding — the two measure
different replay/learner stacks and keeping each linear keeps the
measurement auditable; sync fixes to the pacing/phase logic in both.
"""

from __future__ import annotations

import json
import sys
import threading
import time

import numpy as np


def run_fleet_smoke(num_actors: int = 64, fill_s: float = 4.0,
                    measure_s: float = 6.0, obs_dim: int = 8,
                    batch: int = 64, send_batch: int = 32,
                    pull_every: int = 10,
                    rate_per_actor: float = 256.0) -> dict:
    from distributed_deep_q_tpu.config import Config, NetConfig
    from distributed_deep_q_tpu.replay.replay_memory import ReplayMemory
    from distributed_deep_q_tpu.rpc.replay_server import (
        ReplayFeedClient, ReplayFeedServer)
    from distributed_deep_q_tpu.solver import Solver

    cfg = Config()
    cfg.net = NetConfig(kind="mlp", num_actions=4, hidden=(64, 64))
    cfg.mesh.backend = "cpu"
    cfg.replay.batch_size = batch
    solver = Solver(cfg, obs_dim=obs_dim)

    replay = ReplayMemory(262_144, (obs_dim,), np.float32, seed=0)
    server = ReplayFeedServer(replay)
    server.publish_params(solver.get_weights())
    host, port = server.address

    stop = threading.Event()
    actors_live = threading.Event()
    actors_live.set()
    burst = threading.Event()  # set = unthrottled (capacity measurement)
    burst.set()
    sent = [0] * num_actors
    theta_bytes = [0] * num_actors
    errors: list[str] = []

    def actor(i: int) -> None:
        try:
            rng = np.random.default_rng(i)
            client = ReplayFeedClient(host, port, actor_id=i)
            chunk = {
                "obs": rng.standard_normal(
                    (send_batch, obs_dim)).astype(np.float32),
                "action": rng.integers(0, 4, send_batch).astype(np.int32),
                "reward": rng.standard_normal(send_batch).astype(np.float32),
                "next_obs": rng.standard_normal(
                    (send_batch, obs_dim)).astype(np.float32),
                "discount": np.full(send_batch, 0.99, np.float32),
            }
            t = 0
            interval = send_batch / rate_per_actor
            next_due = time.perf_counter()
            while not stop.is_set():
                if not actors_live.is_set():
                    next_due = time.perf_counter()
                    time.sleep(0.01)
                    continue
                if not burst.is_set():
                    delay = next_due - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
                    next_due = max(next_due + interval, time.perf_counter())
                client.add_transitions(**chunk)
                sent[i] += send_batch
                t += 1
                if t % pull_every == 0:
                    _, w = client.get_params(have_version=-1)
                    if w is not None:
                        theta_bytes[i] += sum(x.nbytes for x in w)
            client.close()
        except Exception as e:  # liveness assertion surface
            errors.append(f"actor {i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=actor, args=(i,), daemon=True)
               for i in range(num_actors)]
    t_spawn = time.perf_counter()
    for th in threads:
        th.start()

    def learner_steps(duration: float) -> float:
        n = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < duration:
            with server.replay_lock:
                b = replay.sample(batch)
            solver.train_step(b)
            n += 1
        import jax
        jax.block_until_ready(solver.state.params)
        return n / (time.perf_counter() - t0)

    # phase A: fill at full burst — the raw ingest-capacity number
    while len(replay) < 5_000 and time.perf_counter() - t_spawn < 60:
        time.sleep(0.05)
    a0 = sum(sent)
    ta = time.perf_counter()
    time.sleep(max(0.5, fill_s - (ta - t_spawn)))
    burst_tps = (sum(sent) - a0) / (time.perf_counter() - ta)
    burst.clear()  # phase C runs paced

    # phase B: idle learner (actors paused)
    actors_live.clear()
    time.sleep(0.2)
    solver.train_step(replay.sample(batch))  # compile outside timing
    idle_sps = learner_steps(measure_s / 2)

    # phase C: concurrent
    actors_live.set()
    sent_before = sum(sent)
    theta_before = sum(theta_bytes)
    t0 = time.perf_counter()
    conc_sps = learner_steps(measure_s)
    dt = time.perf_counter() - t0
    ingest_tps = (sum(sent) - sent_before) / dt
    theta_mb_s = (sum(theta_bytes) - theta_before) / dt / 2**20
    server.publish_params(solver.get_weights())  # exercise re-publish

    stop.set()
    for th in threads:
        th.join(timeout=10)
    streams_seen = len(server.last_seen)
    server.close()
    return {
        "num_actors": num_actors,
        "streams_seen": streams_seen,
        "ingest_capacity_tps": round(burst_tps, 1),
        "ingest_target_tps": round(rate_per_actor * num_actors, 1),
        "ingest_transitions_per_s": round(ingest_tps, 1),
        "learner_idle_steps_per_s": round(idle_sps, 2),
        "learner_concurrent_steps_per_s": round(conc_sps, 2),
        "contention_ratio": round(conc_sps / max(idle_sps, 1e-9), 3),
        "theta_pull_mb_per_s": round(theta_mb_s, 3),
        "replay_size": len(replay),
        "env_steps": server.env_steps,
        "errors": errors,
    }


def run_pixel_fleet_smoke(num_actors: int = 64, fill_s: float = 5.0,
                          measure_s: float = 6.0, batch: int = 32,
                          send_batch: int = 16,
                          rate_per_actor: float = 128.0,
                          frame_hw: int = 36) -> dict:
    """Config-4's REAL data path at fleet scale: socket actors stream
    FRAME chunks into the fused device-PER replay (one stream per actor →
    its own sub-ring) while the learner runs zero-readback fused steps
    under the server lock. Same phase structure as ``run_fleet_smoke``.
    """
    from distributed_deep_q_tpu.config import Config, NetConfig, ReplayConfig
    from distributed_deep_q_tpu.replay.device_per import DevicePERFrameReplay
    from distributed_deep_q_tpu.rpc.replay_server import (
        ReplayFeedClient, ReplayFeedServer)
    from distributed_deep_q_tpu.solver import Solver

    cfg = Config()
    cfg.mesh.backend = "cpu"
    cfg.net = NetConfig(kind="nature_cnn", num_actions=4,
                        frame_shape=(frame_hw, frame_hw))
    cfg.replay = ReplayConfig(capacity=65_536, batch_size=batch, n_step=2,
                              prioritized=True, device_per=True,
                              write_chunk=64)
    solver = Solver(cfg)
    replay = DevicePERFrameReplay(cfg.replay, solver.mesh,
                                  (frame_hw, frame_hw), stack=4,
                                  gamma=0.99, seed=0, write_chunk=64,
                                  num_streams=num_actors)
    server = ReplayFeedServer(replay)
    server.publish_params(solver.get_weights())
    host, port = server.address

    stop = threading.Event()
    actors_live = threading.Event()
    actors_live.set()
    burst = threading.Event()
    burst.set()
    sent = [0] * num_actors
    errors: list[str] = []

    def actor(i: int) -> None:
        try:
            rng = np.random.default_rng(i)
            client = ReplayFeedClient(host, port, actor_id=i)
            client.call("reset_stream")
            frames = rng.integers(0, 255, (send_batch, frame_hw, frame_hw),
                                  dtype=np.uint8)
            t = 0
            interval = send_batch / rate_per_actor
            next_due = time.perf_counter()
            while not stop.is_set():
                if not actors_live.is_set():
                    next_due = time.perf_counter()
                    time.sleep(0.01)
                    continue
                if not burst.is_set():
                    delay = next_due - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
                    next_due = max(next_due + interval, time.perf_counter())
                done = np.zeros(send_batch, bool)
                done[-1] = t % 4 == 3
                client.add_transitions(
                    frame=frames, action=np.zeros(send_batch, np.int32),
                    reward=np.ones(send_batch, np.float32), done=done,
                    boundary=done)
                sent[i] += send_batch
                t += 1
            client.close()
        except Exception as e:
            errors.append(f"actor {i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=actor, args=(i,), daemon=True)
               for i in range(num_actors)]
    t_spawn = time.perf_counter()
    for th in threads:
        th.start()

    def learner_steps(duration: float) -> float:
        import jax
        n = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < duration:
            with server.replay_lock:
                solver.train_step_device_per(replay)
            n += 1
        jax.block_until_ready(solver.state.params)
        return n / (time.perf_counter() - t0)

    # phase A: burst fill → raw pixel ingest capacity
    while not replay.ready(3_000) and time.perf_counter() - t_spawn < 120:
        time.sleep(0.05)
    a0, ta = sum(sent), time.perf_counter()
    time.sleep(max(0.5, fill_s - (ta - t_spawn)))
    burst_tps = (sum(sent) - a0) / (time.perf_counter() - ta)
    burst.clear()

    # phase B: idle fused learner
    actors_live.clear()
    time.sleep(0.2)
    with server.replay_lock:
        solver.train_step_device_per(replay)  # compile outside timing
    idle_sps = learner_steps(measure_s / 2)

    # phase C: concurrent paced ingest + fused learner
    actors_live.set()
    sent_before = sum(sent)
    t0 = time.perf_counter()
    conc_sps = learner_steps(measure_s)
    ingest_tps = (sum(sent) - sent_before) / (time.perf_counter() - t0)

    stop.set()
    for th in threads:
        th.join(timeout=10)
    streams_seen = len(server.last_seen)
    server.close()
    return {
        "num_actors": num_actors,
        "streams_seen": streams_seen,
        "pixel_burst_ingest_tps": round(burst_tps, 1),
        "ingest_target_tps": round(rate_per_actor * num_actors, 1),
        "ingest_transitions_per_s": round(ingest_tps, 1),
        "learner_idle_steps_per_s": round(idle_sps, 2),
        "learner_concurrent_steps_per_s": round(conc_sps, 2),
        "contention_ratio": round(conc_sps / max(idle_sps, 1e-9), 3),
        "replay_size": len(replay),
        "env_steps": server.env_steps,
        "errors": errors,
    }


if __name__ == "__main__":
    n, mode = 64, "vector"
    for arg in sys.argv[1:]:
        if arg.isdigit():
            n = int(arg)
        elif arg in ("vector", "pixel"):
            mode = arg
        else:
            sys.exit(f"usage: fleet_smoke.py [num_actors] [vector|pixel] "
                     f"(got {arg!r})")
    run = run_pixel_fleet_smoke if mode == "pixel" else run_fleet_smoke
    print(json.dumps(run(num_actors=n)))
