"""Attribute the 1M-ring fused sample program's capacity cost (round 5).

PERF §3 blamed the gather lowering (~73 ms isolated) for the 1M flagship
gap, but a clean gather probe (scripts/gather_probe.py) measures the same
shape at ~6 ms. This times the REAL program pair from
``Learner._build_device_per_step`` at 65k vs 1M capacity and then its
capacity-scaled pieces in isolation — validity mask, cumsum CDF, the
stacked-window gather with real (clustered) index patterns — so the
round-5 kernel work targets the true hot spot.

All timings honestly fenced (D2H read of a data-dependent scalar, minus
measured RTT; block_until_ready acks enqueue on this runtime).
"""

from __future__ import annotations

import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import build as bench_build  # noqa: E402
from distributed_deep_q_tpu import config as cfg_mod  # noqa: E402

CHAIN = 32
BATCH = 512


def fence_rtt() -> float:
    x = jnp.zeros((), jnp.int32)
    costs = []
    for _ in range(3):
        y = x + 1
        time.sleep(0.25)
        t0 = time.perf_counter()
        int(jax.device_get(y))
        costs.append(time.perf_counter() - t0)
        x = y
    return float(np.median(costs))


def timed_scalar(fn, *args, reps: int = 3) -> float:
    """Median fenced seconds/call. Only the smallest output leaf is held
    between calls (big outputs free as soon as the call returns), and the
    fence reads that leaf — data-dependent on the whole program."""

    def call():
        out = fn(*args)
        leaf = min(jax.tree_util.tree_leaves(out), key=lambda x: x.size)
        del out
        return leaf

    np.asarray(jax.device_get(call()))
    rtt = fence_rtt()
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(jax.device_get(call()))
        ts.append(time.perf_counter() - t0 - rtt)
    return float(np.median(ts))


def note(msg: str) -> None:
    print(f"[probe] {msg}", file=sys.stderr, flush=True)


def probe_capacity(cap: int, prefill: int) -> None:
    note(f"build cap={cap}")
    solver, replay = bench_build(
        cfg_mod, capacity=cap, batch=BATCH, prioritized=True, pallas=False,
        device_per=True, prefill=prefill)
    # one full chunk to build+cache the program pair
    solver.train_steps_device_per(replay, chain=CHAIN)
    spec_key = (solver._dp_spec, CHAIN)
    sample, train = solver.learner._device_per_steps[spec_key]

    cursors, sizes = replay.device_inputs()
    betas = np.full(CHAIN, 0.5, np.float32)
    keys = solver._next_sample_keys(replay.num_shards, CHAIN)
    rows = replay.dstate

    note("time sample program")
    t_sample = timed_scalar(
        sample, keys, rows.frames, rows.action, rows.reward, rows.done,
        rows.boundary, rows.prio, np.asarray(cursors), np.asarray(sizes),
        betas)

    # isolated capacity-scaled pieces, same shapes as the program
    from distributed_deep_q_tpu.replay.device_per import (
        build_cdf, valid_mask)

    slot_cap = replay.slot_cap
    done, boundary, prio = rows.done, rows.boundary, rows.prio
    cur = jnp.asarray(cursors)
    siz = jnp.asarray(sizes)

    K = 8  # amortize each piece K times per program (RTT ~105 ms here)

    note("time mask+cdf")

    def prep_k(d, b, c, s, p):
        acc = jnp.zeros((), jnp.float32)
        for i in range(K):
            m = valid_mask(d, b, c, s, slot_cap, 4, 3)
            pm = p * m + acc * 0
            cdf, mass = build_cdf(pm)
            (acc2,) = jax.lax.optimization_barrier((mass,))
            acc = acc + acc2
        return acc

    t_prep = timed_scalar(jax.jit(prep_k), done, boundary, cur, siz,
                          prio) / K

    # the real gather: stacked window indices [CHAIN, B, stack] clustered
    # the way _stack_window makes them (4 consecutive rows mod slot_cap).
    # optimization_barrier forces the FULL gather to materialize while the
    # program output stays scalar (no 462 MB output buffers held across
    # reps).
    rng = np.random.default_rng(0)

    def make_widx(i):
        anchors = rng.integers(0, cap, (CHAIN, BATCH)).astype(np.int32)
        offs = np.arange(3, -1, -1, dtype=np.int32)
        return (anchors[..., None] - offs) % slot_cap \
            + (anchors[..., None] // slot_cap) * slot_cap

    def gather_k(frames, idxs):
        acc = jnp.zeros((), jnp.int32)
        for i in range(K):
            idx = idxs[i] + acc * 0  # serialize: no cross-iter overlap hide
            out = frames[idx.reshape(-1)].reshape(idx.shape + (-1,))
            out = jax.lax.optimization_barrier(out)
            acc = acc + out[0, 0, 0, 0].astype(jnp.int32)
        return acc

    note("time gather")
    widxs = jnp.asarray(np.stack([make_widx(i) for i in range(K)]))
    t_gather = timed_scalar(jax.jit(gather_k), rows.frames, widxs) / K

    # flat-random gather, same output bytes (is clustering what hurts?)
    note("time gather flat")
    flat = jnp.asarray(
        rng.integers(0, cap, (K, CHAIN, BATCH, 4)).astype(np.int32))
    t_gflat = timed_scalar(jax.jit(gather_k), rows.frames, flat) / K

    # searchsorted over the big CDF at [B] scale, CHAIN times (the in-scan
    # piece that touches a capacity-sized array), then vectorized in ONE
    # call over [CHAIN*B] (the de-scan candidate)
    def draws(cdf, u):
        acc = jnp.zeros((), jnp.int32)
        for i in range(CHAIN):
            acc = acc + jnp.sum(jnp.searchsorted(cdf, u[i], side="right"))
        return acc

    def draws_vec(cdf, u):
        return jnp.sum(jnp.searchsorted(cdf, u.reshape(-1), side="right"))

    cdf_arr, _ = jax.jit(build_cdf)(prio)
    u = jnp.asarray(rng.random((CHAIN, BATCH), np.float32)) * 100.0
    note("time searchsorted")
    t_ss = timed_scalar(jax.jit(draws), cdf_arr, u)
    t_ssv = timed_scalar(jax.jit(draws_vec), cdf_arr, u)

    print(f"cap {cap:>9}: sample_program {t_sample*1e3:8.2f} ms | "
          f"mask+cdf {t_prep*1e3:7.2f} | "
          f"gather-window {t_gather*1e3:7.2f} | "
          f"gather-flat {t_gflat*1e3:7.2f} | "
          f"searchsorted x{CHAIN} {t_ss*1e3:7.2f} | "
          f"searchsorted vec {t_ssv*1e3:7.2f}")
    del solver, replay


def main() -> None:
    print(f"device: {jax.devices()[0].device_kind}  chain={CHAIN} "
          f"batch={BATCH}")
    probe_capacity(65_536, 40_000)
    probe_capacity(1_048_576, 60_000)


if __name__ == "__main__":
    main()
