"""Time the fused device-PER program pair at 65k vs 1M ring capacity.

Round-5 diagnostic that drove the flat-ring redesign: before it, the
sample program went 28 → 105 ms/chunk between capacities (tile-amplified
meta element-gathers ~42 ms + frame row-gathers ~44 ms, searchsorted
~2 ms — recorded in PERF.md); after the Pallas row-DMA ring + meta pack
both capacities sit near the small-ring cost. Re-run on the TPU box to
re-attribute if the shape of the programs changes.

All timings honestly fenced (D2H read of a data-dependent scalar, minus
measured RTT; block_until_ready acks enqueue on this runtime).
"""

from __future__ import annotations

import os
import sys
import time

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from bench import _fence_rtt, build  # noqa: E402
from distributed_deep_q_tpu import config as cfg_mod  # noqa: E402

CHAIN = 32
BATCH = 512


def note(msg: str) -> None:
    print(f"[probe] {msg}", file=sys.stderr, flush=True)


def probe_capacity(cap: int, prefill: int) -> None:
    note(f"build cap={cap}")
    solver, replay = build(cfg_mod, capacity=cap, batch=BATCH,
                           prioritized=True, pallas=False, device_per=True,
                           prefill=prefill)
    solver.train_steps_device_per(replay, chain=CHAIN)
    sample, train = solver.learner._device_per_steps[
        (solver._dp_spec, CHAIN)]
    cursors, sizes = replay.device_inputs()
    betas = np.full(CHAIN, 0.5, np.float32)
    keys = solver._next_sample_keys(replay.num_shards, CHAIN)
    rows = replay.dstate

    def one_sample():
        out = sample(keys, rows.frames, rows.action, rows.reward,
                     rows.done, rows.boundary, rows.prio,
                     np.asarray(cursors), np.asarray(sizes), betas)
        int(jax.device_get(out[2][0, 0]))
        return out

    note("time sample program")
    metas, win, idx = one_sample()
    rtt = _fence_rtt(solver)
    ts = []
    for _ in range(7):
        del metas, win, idx
        t0 = time.perf_counter()
        metas, win, idx = one_sample()
        ts.append(time.perf_counter() - t0 - rtt)
    t_sample = float(np.median(ts))

    note("time train program")
    state, prio, maxp = solver.state, rows.prio, rows.maxp
    reps = 4

    def run_train(state, prio, maxp):
        for _ in range(reps):
            state, prio, maxp, m = train(state, metas, win, idx, prio,
                                         maxp)
        int(jax.device_get(state.step))
        return state, prio, maxp

    state, prio, maxp = run_train(state, prio, maxp)
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        state, prio, maxp = run_train(state, prio, maxp)
        ts.append((time.perf_counter() - t0 - rtt) / reps)
    t_train = float(np.median(ts))
    total = t_sample + t_train
    print(f"cap {cap:>9}: sample {t_sample*1e3:8.2f} ms/chunk | "
          f"train {t_train*1e3:8.2f} ms/chunk | per-step "
          f"{1e3*total/CHAIN:6.3f} ms | {CHAIN/total:7.1f} steps/s",
          flush=True)
    del solver, replay


def main() -> None:
    print(f"device: {jax.devices()[0].device_kind}  chain={CHAIN} "
          f"batch={BATCH}")
    probe_capacity(65_536, 40_000)
    probe_capacity(1_000_000, 60_000)


if __name__ == "__main__":
    main()
