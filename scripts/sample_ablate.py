"""Ablate the fused sample program at 1M capacity to find its hot spot.

Variants (same shard_map/jit structure as Learner._build_device_per_step's
sample program, chain=32, batch=512):
  full        — the real program
  nosearch    — inverse-CDF searchsorted replaced by direct u*cap index
  nocompose   — + meta composition dropped (windows from raw idx)
  gather_only — the two frame-row gathers alone, fixed indices
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench import build, _fence_rtt  # noqa: E402
from distributed_deep_q_tpu import config as cfg_mod  # noqa: E402
from distributed_deep_q_tpu.parallel.mesh import AXIS_DP  # noqa: E402
from distributed_deep_q_tpu.replay.device_per import (  # noqa: E402
    _stack_window, compose_meta, fused_sample_prep, gather_rows)

CHAIN, BATCH = 32, 512


def note(m):
    print(f"[a] {m}", file=sys.stderr, flush=True)


def main() -> None:
    cap = 1_000_000
    note("build")
    solver, replay = build(cfg_mod, capacity=cap, batch=BATCH,
                           prioritized=True, pallas=False, device_per=True,
                           prefill=60_000)
    mesh = solver.mesh
    slot_cap, stack, n_step, gamma = (replay.slot_cap, replay.stack,
                                      replay.n_step, replay.gamma)
    per_shard = BATCH // replay.num_shards
    num_shards = replay.num_shards
    rows = replay.dstate
    cursors, sizes = replay.device_inputs()
    betas = np.full(CHAIN, 0.5, np.float32)
    keys = solver._next_sample_keys(replay.num_shards, CHAIN)

    S = P(AXIS_DP)
    SK = P(None, AXIS_DP)

    def make(variant):
        def sample_fn(keys, frames, action, reward, done, boundary, prio,
                      cursors, sizes, betas):
            shard_rows = {"action": action, "reward": reward, "done": done,
                          "boundary": boundary, "prio": prio}
            pm, cdf, mass, n_glob = fused_sample_prep(
                shard_rows, cursors, sizes, slot_cap, stack, n_step)
            k = keys[0]
            u = jax.vmap(lambda kk: jax.random.uniform(kk, (per_shard,)))(k)
            if variant == "full":
                idx = jnp.searchsorted(cdf, u * mass, side="right")
            else:
                idx = (u * pm.shape[0]).astype(jnp.int32)
            idx = jnp.clip(idx, 0, pm.shape[0] - 1)
            sub, local = idx // slot_cap, idx % slot_cap
            fl, fs = local.reshape(-1), sub.reshape(-1)
            if variant in ("full", "nosearch"):
                meta, oflat, ovalid, nflat, nvalid = compose_meta(
                    shard_rows, fl, fs, slot_cap, stack, n_step, gamma)
            else:
                oflat, ovalid = _stack_window(boundary, fl, fs, slot_cap,
                                              stack)
                nflat, nvalid = oflat, ovalid
            lead = (CHAIN, per_shard)
            oflat = oflat.reshape(lead + oflat.shape[1:])
            ovalid = ovalid.reshape(lead + ovalid.shape[1:])
            nflat = nflat.reshape(lead + nflat.shape[1:])
            nvalid = nvalid.reshape(lead + nvalid.shape[1:])
            obs = gather_rows(frames, oflat, ovalid)
            nobs = gather_rows(frames, nflat, nvalid)
            return obs, nobs, idx.astype(jnp.int32)

        return jax.jit(shard_map(
            sample_fn, mesh=mesh,
            in_specs=(S, S, S, S, S, S, S, S, S, P()),
            out_specs=(SK, SK, SK), check_vma=False))

    def gather_only():
        rng = np.random.default_rng(0)
        anchors = rng.integers(0, cap, (CHAIN, BATCH)).astype(np.int32)
        offs = np.arange(3, -1, -1, dtype=np.int32)
        widx = jnp.asarray((anchors[..., None] - offs) % slot_cap
                           + (anchors[..., None] // slot_cap) * slot_cap)
        valid = jnp.ones(widx.shape, bool)

        def fn(frames, widx, valid):
            return (gather_rows(frames, widx, valid),
                    gather_rows(frames, widx, valid),
                    widx[..., 0])

        return jax.jit(shard_map(
            fn, mesh=mesh, in_specs=(S, SK, SK), out_specs=(SK, SK, SK),
            check_vma=False)), widx, valid

    rtt = None
    for variant in ("full", "nosearch", "nocompose", "gather_only"):
        note(variant)
        if variant == "gather_only":
            fn, widx, valid = gather_only()
            args = (rows.frames, widx, valid)
        else:
            fn = make(variant)
            args = (keys, rows.frames, rows.action, rows.reward, rows.done,
                    rows.boundary, rows.prio, np.asarray(cursors),
                    np.asarray(sizes), betas)

        def call():
            out = fn(*args)
            int(jax.device_get(out[2][0, 0]))

        call()
        if rtt is None:
            rtt = _fence_rtt(solver)
        ts = []
        for _ in range(7):
            t0 = time.perf_counter()
            call()
            ts.append(time.perf_counter() - t0 - rtt)
        print(f"{variant:>12}: {1e3 * float(np.median(ts)):8.2f} ms/chunk",
              flush=True)


if __name__ == "__main__":
    main()
