#!/usr/bin/env python
"""Elasticity bench (ISSUE 17): shard-handoff wall time + remap churn.

Two measurements behind the PERF.md §17 row:

- **Handoff wall time** — a live ``ReplayFeedServer`` holding a labeled
  replay shard is gracefully retired through
  ``membership.export_shard`` (drain → GenerationStore snapshot,
  manifest-committed) and a fresh server warm-boots it through
  ``membership.import_shard``. Export and import are timed separately
  over ``--repeats`` rounds; the row carries the medians and the
  max relative spread (the bench_diff tolerance).
- **Remap fraction** — the share of the acting fleet whose owner
  changes across 2→4 (grow) and 4→2 (shrink) host-set steps of
  ``assign_fleet``. Deterministic given the ring, so a drift here is a
  ring-layout change, not noise: both directions should stay well under
  the naive-modulo ~0.75 reshuffle.

Output is one flat JSON dict on stdout (bench_diff-ready)::

    python scripts/bench_elasticity.py [--rows 4096] [--repeats 5]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from distributed_deep_q_tpu.actors import membership as ms  # noqa: E402
from distributed_deep_q_tpu.actors.assignment import assign_fleet, host_tokens
from distributed_deep_q_tpu.replay.replay_memory import ReplayMemory
from distributed_deep_q_tpu.rpc.replay_server import (
    ReplayFeedClient, ReplayFeedServer)


def _fill(server: ReplayFeedServer, rows: int) -> None:
    """Feed ``rows`` labeled transitions through the real wire path so
    the exported shard is what production would hand off."""
    host, port = server.address
    client = ReplayFeedClient(host, port, actor_id=1)
    try:
        chunk = 512
        seq = 0
        for start in range(0, rows, chunk):
            n = min(chunk, rows - start)
            ids = np.arange(start, start + n, dtype=np.float32)
            obs = np.stack([ids, ids], axis=1)
            seq += 1
            client.call("add_transitions", flush_seq=seq, obs=obs,
                        action=np.zeros(n, np.int32),
                        reward=np.zeros(n, np.float32), next_obs=obs,
                        discount=np.ones(n, np.float32))
    finally:
        client.close()


def bench_handoff(rows: int, repeats: int, tmp: str) -> dict:
    exports, imports = [], []
    # round 0 is a discarded warmup: it pays the lazy persistence-module
    # imports and filesystem cache faults that production hosts paid at
    # boot, which would otherwise dominate the recorded spread
    for r in range(repeats + 1):
        replay = ReplayMemory(max(rows, 1), (2,))
        server = ReplayFeedServer(replay)
        _fill(server, rows)
        path = f"{tmp}/handoff-{r}"
        export = ms.export_shard(server, path)
        replay2 = ReplayMemory(max(rows, 1), (2,))
        server2, imported = ms.import_shard(replay2, path)
        server2.close()
        if imported["rows"] != rows or export["rows"] != rows:
            raise SystemExit(
                f"handoff lost rows: exported {export['rows']}, "
                f"imported {imported['rows']}, expected {rows}")
        if r > 0:
            exports.append(export["export_ms"])
            imports.append(imported["import_ms"])

    def spread(xs: list[float]) -> float:
        m = statistics.median(xs)
        return (max(xs) - min(xs)) / m if m else 0.0

    return {
        "handoff_export_ms": round(statistics.median(exports), 3),
        "handoff_import_ms": round(statistics.median(imports), 3),
        "handoff_rows": rows,
        "elasticity_spread": round(max(spread(exports), spread(imports)), 4),
    }


def bench_remap(fleet: int) -> dict:
    """Owner-change fraction across 2→4 (grow) and 4→2 (shrink)."""

    def owners(hosts):
        return {g: h for h, v in assign_fleet(fleet, hosts).items()
                for g in v}

    o2, o4 = owners(host_tokens(2)), owners(host_tokens(4))
    moved_grow = sum(o2[g] != o4[g] for g in range(fleet))
    moved_shrink = sum(o4[g] != o2[g] for g in range(fleet))
    return {
        "fleet_size": fleet,
        "remap_fraction_grow": round(moved_grow / fleet, 4),
        "remap_fraction_shrink": round(moved_shrink / fleet, 4),
    }


def bench_tenants(repeats: int) -> dict:
    """Multi-tenant serving + executor control-path costs (ISSUE 20,
    PERF.md §19): θ swap latency on a live server, the shadow mirror's
    toll on primary reply latency, and the ScaleExecutor apply path
    against an inert fleet stub (control-plane bookkeeping only — child
    boot time is the supervisor's spawn cost, benched nowhere because
    it is dominated by the child's jax import)."""
    import time

    from distributed_deep_q_tpu.actors.autoscaler import Decision
    from distributed_deep_q_tpu.actors.executor import ScaleExecutor
    from distributed_deep_q_tpu.config import NetConfig
    from distributed_deep_q_tpu.models.policy import BatchedPolicy
    from distributed_deep_q_tpu.rpc.inference_server import (
        InferenceClient, InferenceServer)

    net = NetConfig(kind="mlp", hidden=(32, 32), num_actions=5)
    obs = np.random.default_rng(0).standard_normal((8, 6)).astype(np.float32)

    def drive(tenants: tuple, n: int = 150) -> tuple[float, float]:
        """-> (median primary reply ms, median set_params µs)."""
        policy = BatchedPolicy(net, seed=0, obs_dim=6, buckets=(8,))
        server = InferenceServer(policy, max_batch=8, cutoff_us=100,
                                 tenants=tenants)
        w = policy.get_weights()
        server.set_params(w, version=1)
        for tag in tenants:
            server.set_params(w, version=1, tenant=tag)
        host, port = server.address
        client = InferenceClient(host, port, actor_id=0)
        try:
            for _ in range(20):  # warmup: compile + socket caches
                client.infer(obs)
            lat = []
            for _ in range(n):
                t0 = time.perf_counter()
                client.infer(obs)
                lat.append(1e3 * (time.perf_counter() - t0))
            swaps = []
            version = 2
            for _ in range(64):
                t0 = time.perf_counter()
                server.set_params(w, version=version)
                swaps.append(1e6 * (time.perf_counter() - t0))
                version += 1
        finally:
            client.close()
            server.close()
        return statistics.median(lat), statistics.median(swaps)

    class _StubFleet:
        def __init__(self):
            self.n = 4

        def fleet_size(self):
            return self.n

        def actor_ids(self):
            return list(range(self.n))

        def grow(self):
            self.n += 1
            return self.n - 1

        def retire(self, i):
            self.n -= 1
            return True

        def reap_actor(self, i):
            return self.retire(i)

    plain, shadowed, swap_us, apply_us = [], [], [], []
    for _ in range(repeats):
        ms_plain, _ = drive(())
        ms_shadow, sw = drive(("shadow:cand",))
        plain.append(ms_plain)
        shadowed.append(ms_shadow)
        swap_us.append(sw)
        fleet = _StubFleet()
        ex = ScaleExecutor(fleet, rate_limit_s=0.0, drain_s=0.0)
        t0 = time.perf_counter()
        ex.apply([Decision("grow_actors", "capacity_recovered", "", "",
                           1.0, 1.0, 0.0, 0.0, 4, 5, 0.0)])
        ex.apply([Decision("shrink_actors", "ingest_shed", "k", "m",
                           9.0, 0.0, 2.0, 1.5, 5, 4, 0.0)])
        apply_us.append(1e6 * (time.perf_counter() - t0) / 2)

    def spread(xs: list[float]) -> float:
        m = statistics.median(xs)
        return (max(xs) - min(xs)) / m if m else 0.0

    pl, sh = statistics.median(plain), statistics.median(shadowed)
    return {
        "tenant_swap_us": round(statistics.median(swap_us), 1),
        "shadow_overhead_pct": round(1e2 * (sh - pl) / pl, 2) if pl else 0.0,
        "executor_apply_us": round(statistics.median(apply_us), 1),
        "tenant_spread": round(max(spread(plain), spread(shadowed),
                                   spread(swap_us)), 4),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=4096)
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--fleet", type=int, default=64)
    ap.add_argument("--tenant-repeats", type=int, default=3)
    args = ap.parse_args(argv)
    import tempfile
    with tempfile.TemporaryDirectory(prefix="bench-elasticity-") as tmp:
        out = bench_handoff(args.rows, args.repeats, tmp)
    out.update(bench_remap(args.fleet))
    out.update(bench_tenants(args.tenant_repeats))
    print(json.dumps(out, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
