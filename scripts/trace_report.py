#!/usr/bin/env python
"""Merge per-process trace shards into one Perfetto-loadable trace and
print the data-path attribution.

    python scripts/trace_report.py traces/trace-*.json [--out FILE]

- merges the Chrome trace-event shards ``tracing.export()`` wrote (one
  per process), shifting each shard's timestamps by its recorded
  ``skew_s`` so every event sits on the replay server's clock;
- prints the per-(process, thread) SELF-time attribution table — each
  stage's exclusive time, its share of thread wall time, and the
  untraced residue, so "stages sum to ≈ wall" is checkable at a glance;
- prints causal-integrity counters: orphan spans (a ``parent`` id found
  in no shard — dropped or never exported), per-shard span drops, and
  the clock-skew estimates applied;
- ``--strict`` exits non-zero on orphans or drops
  (``scripts/chaos_smoke.py`` uses the same orphan check as an
  assertion).

Stdlib-only, like the tracer itself: ``tracing.py`` is loaded directly
by file path so post-processing a trace needs no jax on the host.
"""

from __future__ import annotations

import argparse
import glob
import importlib.util
import json
import os
import sys


def _load_tracing():
    """Load ``distributed_deep_q_tpu/tracing.py`` without importing the
    package (whose ``__init__`` pulls in jax): the attribution helpers
    are shared with ``bench.py --trace-ingest``, not duplicated here."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "distributed_deep_q_tpu", "tracing.py")
    spec = importlib.util.spec_from_file_location("_ddq_tracing", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_shards(paths: list[str]) -> list[dict]:
    """Parse shard files; raises ValueError naming the bad file."""
    docs = []
    for p in paths:
        try:
            with open(p) as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            raise ValueError(f"{p}: unreadable trace shard ({e})")
        if "traceEvents" not in doc:
            raise ValueError(f"{p}: no traceEvents key (not a trace shard)")
        doc["_path"] = p
        docs.append(doc)
    return docs


def merge_shards(docs: list[dict]) -> tuple[list[dict], list[dict]]:
    """One event list on a common clock + per-shard info rows.

    Each shard's ``otherData.skew_s`` is the offset of the SERVER clock
    relative to that process (NTP-style, estimated from reply stamps), so
    ``ts + skew_s`` puts the event on the server clock. The server's own
    shard (and any process that never sampled skew) carries 0.0.
    """
    events: list[dict] = []
    info: list[dict] = []
    for doc in docs:
        other = doc.get("otherData", {})
        shift_us = float(other.get("skew_s", 0.0)) * 1e6
        for ev in doc["traceEvents"]:
            if ev.get("ph") in ("X", "i"):
                ev = dict(ev, ts=ev["ts"] + shift_us)
            events.append(ev)
        info.append({
            "path": doc["_path"],
            "pid": other.get("pid"),
            "skew_ms": round(float(other.get("skew_s", 0.0)) * 1e3, 3),
            "spans_dropped": int(other.get("spans_dropped", 0)),
            "events": sum(1 for e in doc["traceEvents"]
                          if e.get("ph") in ("X", "i")),
        })
    return events, info


def orphan_spans(events: list[dict]) -> list[dict]:
    """Events whose ``parent`` id resolves to no exported span in ANY
    shard. Cross-process parents are expected (a server-side span's
    parent is the client's ``rpc_call`` span), so the id set spans the
    whole merge; instants carry span id 0 and can never be parents."""
    ids = {e["args"]["span"] for e in events
           if e.get("ph") == "X" and "args" in e}
    ids.discard(0)
    return [e for e in events
            if e.get("ph") in ("X", "i") and "args" in e
            and e["args"].get("parent", 0) != 0
            and e["args"]["parent"] not in ids]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("shards", nargs="+",
                    help="trace-<pid>.json shard files (or globs)")
    ap.add_argument("--out", default=None,
                    help="write the merged Perfetto JSON here "
                         "(default: <dir of first shard>/merged.json)")
    ap.add_argument("--wall", type=float, default=None,
                    help="wall-clock seconds of the traced window, for "
                         "the per-thread share column")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero on orphan spans or span drops")
    args = ap.parse_args(argv)

    paths = sorted({p for pat in args.shards for p in glob.glob(pat)})
    if not paths:
        print("error: no shard files match", file=sys.stderr)
        return 1
    tracing = _load_tracing()
    try:
        docs = load_shards(paths)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    events, info = merge_shards(docs)
    spans = [e for e in events if e.get("ph") == "X"]
    orphans = orphan_spans(events)
    dropped = sum(row["spans_dropped"] for row in info)

    out_path = args.out or os.path.join(
        os.path.dirname(paths[0]) or ".", "merged.json")
    merged = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "shards": [row["path"] for row in info],
            "spans_dropped": dropped,
            "orphan_spans": len(orphans),
        },
    }
    with open(out_path, "w") as fh:
        json.dump(merged, fh)

    print("== shards ==")
    for row in info:
        print(f"  {row['path']}: pid={row['pid']} events={row['events']} "
              f"skew={row['skew_ms']}ms dropped={row['spans_dropped']}")
    stages = sorted({e["name"] for e in spans})
    pids = sorted({e["pid"] for e in spans})
    print(f"\n== coverage ==\n  {len(spans)} spans, "
          f"{len(stages)} distinct stages across {len(pids)} process(es)")
    print(f"  stages: {', '.join(stages) or '-'}")
    print(f"\n== attribution (self time) ==")
    print(tracing.attribution_table(events, wall_s=args.wall))
    print(f"\n== causal integrity ==")
    print(f"  orphan spans: {len(orphans)}")
    for e in orphans[:10]:
        print(f"    ! {e['name']} pid={e['pid']} tid={e['tid']} "
              f"parent={e['args']['parent']}")
    print(f"  spans dropped at record time: {dropped}")
    print(f"\nmerged trace -> {out_path} (load in ui.perfetto.dev)")
    if args.strict and (orphans or dropped):
        print("strict: FAILED (orphans or drops present)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
