"""Diagnose the 1M-ring gather slowness (VERDICT r4 missing #1 / PERF §3).

An isolated 65k-row gather from the 1M-row uint8 frame ring measures
~73 ms for a 462 MB output — far off the ~1.1 ms HBM copy bound. This
probe separates the candidate causes before a kernel is designed:

- capacity scaling: is the cost O(output) or O(ring)?
- dtype tiling: uint8 rows live in (32,128) HBM tiles, so a row-gather
  may read 32x its bytes; an int32 view ([cap, 1764]) amplifies only 8x.
- index order: XLA's gather may have a fast path for sorted indices.
- Pallas row-DMA: per-row async copies straight HBM->HBM, no tiles read
  beyond the row's own granules.

Honest fencing per MEMORY: block_until_ready acks enqueue on this
tunneled runtime; every timed window here ends with a D2H read of a
scalar that data-depends on every gather, minus the measured RTT.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

ROW = 7056          # 84*84
N_OUT = 65_536      # rows per gather (= chain 32 x batch 512 x stack 4 / 2)
K = 8               # gathers per timed program


def fence_rtt() -> float:
    x = jnp.zeros((), jnp.int32)
    costs = []
    for _ in range(3):
        y = x + 1
        time.sleep(0.25)
        t0 = time.perf_counter()
        int(jax.device_get(y))
        costs.append(time.perf_counter() - t0)
        x = y
    return float(np.median(costs))


def timed(fn, *args, reps=3) -> float:
    """Median seconds per call of jitted fn returning a scalar, fenced."""
    r = fn(*args)
    int(jax.device_get(r))  # compile + first run
    rtt = fence_rtt()
    outs = []
    for _ in range(reps):
        t0 = time.perf_counter()
        int(jax.device_get(fn(*args)))
        outs.append(time.perf_counter() - t0 - rtt)
    return float(np.median(outs))


def probe_xla(frames: jax.Array, idxs: jax.Array) -> float:
    """K gathers in one program; returns s per gather."""

    @jax.jit
    def run(frames, idxs):
        acc = jnp.zeros((), jnp.int32)
        for i in range(K):
            out = frames[idxs[i]]
            acc = acc + jnp.sum(out[:, :1].astype(jnp.int32))
        return acc

    return timed(run, frames, idxs) / K


def main() -> None:
    rng = np.random.default_rng(0)
    print(f"device: {jax.devices()[0].device_kind}")
    print(f"output rows per gather: {N_OUT}  row bytes: {ROW}  "
          f"output MB: {N_OUT * ROW / 1e6:.0f}")

    for cap in (65_536, 262_144, 1_048_576):
        idx = rng.integers(0, cap, (K, N_OUT)).astype(np.int32)
        idx_sorted = np.sort(idx, axis=1)

        frames8 = jnp.zeros((cap, ROW), jnp.uint8)
        t8 = probe_xla(frames8, jnp.asarray(idx))
        t8s = probe_xla(frames8, jnp.asarray(idx_sorted))
        del frames8

        frames32 = jnp.zeros((cap, ROW // 4), jnp.int32)
        t32 = probe_xla(frames32, jnp.asarray(idx))
        t32s = probe_xla(frames32, jnp.asarray(idx_sorted))
        del frames32

        bw = N_OUT * ROW / 1e9
        print(f"cap {cap:>9}: uint8 {t8*1e3:7.2f} ms ({bw/t8:6.1f} GB/s) | "
              f"uint8-sorted {t8s*1e3:7.2f} | "
              f"int32 {t32*1e3:7.2f} ({bw/t32:6.1f} GB/s) | "
              f"int32-sorted {t32s*1e3:7.2f}")


if __name__ == "__main__":
    main()
