"""Static-analysis gate — run every analyzer pass; exit non-zero on
findings.

    python scripts/analysis_gate.py [--root DIR]

Runs the lock-discipline checker, JAX purity lint, RPC protocol-drift
detector, and config-key checker over the tree and prints one
``path:line: [rule] message`` line per finding. Exit status 0 = clean,
1 = findings. Pure-CPU AST work, no jax import, sub-second — cheap
enough for CI and for ``scripts/chaos_smoke.py``'s pre-flight check.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--root", default=None,
                        help="repo root (default: auto-detected)")
    args = parser.parse_args(argv)

    from distributed_deep_q_tpu.analysis import run_all

    findings = run_all(args.root)
    for f in findings:
        print(f)
    if findings:
        print(f"analysis gate: FAILED — {len(findings)} finding(s)")
        return 1
    print("analysis gate: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
