"""Static-analysis gate — run every analyzer pass; exit non-zero on
findings.

    python scripts/analysis_gate.py [--root DIR] [--rule PREFIX]...
                                    [--json] [--list-rules]

Runs the lock/CV-discipline checker, thread-lifecycle registry,
blocking-while-locked detector, JAX purity lint, RPC protocol-drift +
verb-class detector, config-key, durability, and metric-key checkers
over the tree and prints one ``path:line: [rule] message`` line per
finding (or one JSON object per line with ``--json``, for CI diffing).
``--rule`` (repeatable) filters findings to the given rule name or
pass prefix; an unknown prefix is an error (exit 2), so a pre-flight
whitelist can never silently match nothing. Exit status 0 = clean,
1 = findings. Pure-CPU AST work, no jax import, sub-second — cheap
enough for CI and for ``scripts/chaos_smoke.py``'s pre-flight check.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _light_package() -> None:
    """Bind ``distributed_deep_q_tpu`` to a bare namespace module so the
    analysis subpackage imports WITHOUT the runtime package __init__
    (jax, flax, numpy — ~1 s of interpreter start). The passes are
    stdlib-AST-only by design; this keeps the CLI's 'no jax import'
    promise true and the gate sub-second. In-process callers (tests,
    chaos_smoke) that already imported the real package are unaffected."""
    if "distributed_deep_q_tpu" not in sys.modules:
        import types
        pkg = types.ModuleType("distributed_deep_q_tpu")
        pkg.__path__ = [os.path.join(_REPO, "distributed_deep_q_tpu")]
        sys.modules["distributed_deep_q_tpu"] = pkg


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--root", default=None,
                        help="repo root (default: auto-detected)")
    parser.add_argument("--rule", action="append", default=[],
                        metavar="PREFIX",
                        help="only report findings whose rule matches "
                             "this name or pass prefix (repeatable)")
    parser.add_argument("--json", action="store_true",
                        help="one JSON finding object per line")
    parser.add_argument("--list-rules", action="store_true",
                        help="print every rule the suite can emit, exit 0")
    args = parser.parse_args(argv)

    _light_package()
    from distributed_deep_q_tpu.analysis import KNOWN_RULES, run_all

    if args.list_rules:
        for rule in KNOWN_RULES:
            print(rule)
        return 0

    def matches(rule: str, prefix: str) -> bool:
        return rule == prefix or rule.startswith(prefix + ".")

    for prefix in args.rule:
        if not any(matches(rule, prefix) for rule in KNOWN_RULES):
            print(f"analysis gate: unknown rule prefix {prefix!r} "
                  "(see --list-rules)", file=sys.stderr)
            return 2

    findings = run_all(args.root)
    if args.rule:
        findings = [f for f in findings
                    if any(matches(f.rule, p) for p in args.rule)]
    for f in findings:
        if args.json:
            print(json.dumps({"rule": f.rule, "path": f.path,
                              "line": f.line, "message": f.message}))
        else:
            print(f)
    # with --json, stdout stays machine-parseable (one object per line);
    # the human verdict goes to stderr
    verdict_out = sys.stderr if args.json else sys.stdout
    if findings:
        print(f"analysis gate: FAILED — {len(findings)} finding(s)",
              file=verdict_out)
        return 1
    print("analysis gate: clean", file=verdict_out)
    return 0


if __name__ == "__main__":
    rc = main()
    # skip interpreter teardown: refcount-freeing the ~150k cached AST
    # nodes costs ~0.3 s of pure exit latency. Nothing here needs
    # atexit/finalizers — flush and leave.
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
