#!/usr/bin/env python
"""Diff two bench result files (BENCH_r*.json) and flag regressions.

Usage::

    python scripts/bench_diff.py BENCH_r05.json BENCH_r06.json
    python scripts/bench_diff.py --tolerance 0.05 old.json new.json

Compares every numeric metric present in both files. A metric has
REGRESSED when it moves in its bad direction (throughput down, latency /
op-count up) by more than its tolerance — the larger recorded ``spread``
of the two runs when one exists (benches record run-to-run relative
spread next to gated metrics), else ``--tolerance`` (default 2%).

Keys listed under ``tunnel_bound_keys`` are measurements of the
benchmarking transport, not of the system (EVAL_PROTOCOL.md) — their
regressions are ANNOTATED but never fail the diff. The CANDIDATE run's
list wins (falling back to the baseline's when absent): when a bench
graduates a key out of the tunnel set — e.g. ``ingest_curve`` once the
columnar drain made it learner-bound — diffs against old baselines gate
it immediately. Exit status is 1 iff a non-tunnel-bound metric
regressed; stdlib only, no repo imports, so it runs anywhere the jsons
land.
"""

from __future__ import annotations

import argparse
import json
import sys

# metric -> its recorded run-to-run spread key, where the bench doesn't
# follow the "<prefix>_steps_per_s" / "<prefix>_spread" convention
SPREAD_KEY = {
    "value": "flagship_spread",
    "idle_uniform_steps_per_s": "idle_spread",
    "pallas_off_steps_per_s": "idle_spread",
    "flagship_under_ingest_steps_per_s": "under_ingest_spread",
    # linearity ratios divide two curve points, so their run-to-run
    # spread is the (first-order) SUM of the points' spreads — the bench
    # records that sum next to each ratio
    "multihost_linearity_2x": "multihost_linearity_2x_spread",
    "multihost_linearity_4x": "multihost_linearity_4x_spread",
    # health-plane overhead rows (ISSUE 13) share one measured spread
    "health_sample_us": "health_spread",
    "health_verdict_us": "health_spread",
    "health_disabled_us": "health_spread",
    "mfu_live": "flagship_spread",
    # learn_metrics on-vs-off overhead (ISSUE 16): the pct divides two
    # timed points, so its noise is the sum of their spreads — recorded
    # as learn_spread (learn_off/on_steps_per_s follow the automatic
    # "<prefix>_spread" convention and need no entry here)
    "learn_overhead_pct": "learn_spread",
    # elasticity rows (ISSUE 17) share one measured handoff spread; the
    # remap fractions are ring properties (deterministic given the host
    # set) but ride the same key so a ring change gates like noise would
    "handoff_export_ms": "elasticity_spread",
    "handoff_import_ms": "elasticity_spread",
    "remap_fraction_grow": "elasticity_spread",
    "remap_fraction_shrink": "elasticity_spread",
    # multi-tenant serving rows (ISSUE 20) share one measured spread;
    # shadow_overhead_pct divides two timed latencies, so its noise is
    # the sum of their spreads — folded into the same recorded key
    "tenant_swap_us": "tenant_spread",
    "shadow_overhead_pct": "tenant_spread",
    "executor_apply_us": "tenant_spread",
}

# substrings marking metrics where UP is the bad direction
# (_rpcs: cross_host_replay_rpcs is a badness LEDGER — any cross-host
# replay traffic is a sharding violation, so up must gate, and the
# common old=0 case makes any appearance an infinite regression)
_LOWER_BETTER = ("_ms", "_fusions", "_convs", "_copies", "fusions",
                 "spread", "_rpcs", "_us", "overhead_pct",
                 # remap fraction: more of the fleet reconnecting per
                 # membership change is strictly worse (reconnect storm)
                 "remap_fraction")
# keys that are configuration echoes / identities, not metrics
# (max_in_flight_rows is the writers' backpressure watermark — a state
# echo of the pacing loop, not a quality axis with a bad direction;
# inference_curve's SLO/batch knobs are config echoes, sheds a state
# echo, and local_actions_per_s the comparison-host baseline the
# speedup already folds in — gating it would gate host CPU noise;
# multihost_curve's n_hosts is the point's identity and dispatch_k its
# calibration echo)
_SKIP = ("_chain_k", "_vs_", "vs_baseline", "ring_capacity",
         "flagship_batch", "concurrent_writers", "peak_flops", "n", "rc",
         "flops_per_step", "max_in_flight_rows", "inference_slo_ms",
         "inference_max_batch", "inference_cutoff_us", "sheds",
         "local_actions_per_s", "n_hosts", "dispatch_k", "n_envs",
         # elasticity bench identities: rows carried per handoff and the
         # acting fleet the remap fractions are computed over
         "handoff_rows", "fleet_size",
         # config echo: the live-vs-offline MFU agreement bound bench.py
         # asserts; the gated quality axes are mfu / mfu_live themselves
         "mfu_live_tolerance")


def _parsed(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    return doc.get("parsed", doc) if isinstance(doc, dict) else {}


def _lower_is_better(key: str) -> bool:
    return any(tag in key for tag in _LOWER_BETTER)


def _skipped(key: str) -> bool:
    return key in _SKIP or any(tag in key for tag in _SKIP if tag != "n")


def _spread_for(key: str, a: dict, b: dict) -> float | None:
    sk = SPREAD_KEY.get(key)
    if sk is None and key.endswith("_steps_per_s"):
        sk = key[: -len("_steps_per_s")] + "_spread"
    if sk is None:
        return None
    vals = [d[sk] for d in (a, b) if isinstance(d.get(sk), (int, float))]
    return max(vals) if vals else None


def _flatten(d: dict, prefix: str = "") -> dict:
    """Nested curve rows (``ingest_curve``, ``inference_curve``) become
    dotted keys; each nested dict's own ``spread`` rides along under its
    dotted name and becomes the tolerance for its siblings."""
    out = {}
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, f"{key}."))
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            out[key] = float(v)
    return out


def diff(a: dict, b: dict, tolerance: float):
    """-> (rows, failed). Each row: (key, old, new, rel_delta, tol,
    status) with status in {ok, improved, regressed, tunnel-bound}."""
    # candidate's tunnel list wins: a bench that PROMOTES a key out of
    # the tunnel set (ingest_curve, ISSUE 8) starts gating it even
    # against baselines that still listed it
    tunnel = set(b.get("tunnel_bound_keys")
                 or a.get("tunnel_bound_keys") or [])
    fa, fb = _flatten(a), _flatten(b)
    rows, failed = [], False
    for key in sorted(fa.keys() & fb.keys()):
        if _skipped(key) or key.endswith(".spread"):
            continue
        old, new = fa[key], fb[key]
        if key.endswith("spread"):
            continue
        tol = _spread_for(key, a, b)
        if tol is None:
            # nested curves record spread alongside the metric
            tol = fa.get(key.rsplit(".", 1)[0] + ".spread")
        if tol is None:
            tol = tolerance
        delta = (new - old) / abs(old) if old else (0.0 if new == old
                                                    else float("inf"))
        bad = -delta if _lower_is_better(key) else delta
        if bad < -tol:
            root = key.split(".", 1)[0]
            if root in tunnel or key in tunnel:
                status = "tunnel-bound"
            else:
                status, failed = "regressed", True
        elif bad > tol:
            status = "improved"
        else:
            status = "ok"
        rows.append((key, old, new, delta, tol, status))
    return rows, failed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("old", help="baseline BENCH_r*.json")
    ap.add_argument("new", help="candidate BENCH_r*.json")
    ap.add_argument("--tolerance", type=float, default=0.02,
                    help="relative tolerance for metrics with no "
                         "recorded spread (default 0.02)")
    ap.add_argument("--all", action="store_true",
                    help="print every compared metric, not just moves")
    args = ap.parse_args(argv)

    rows, failed = diff(_parsed(args.old), _parsed(args.new),
                        args.tolerance)
    if not rows:
        print("no shared numeric metrics to compare")
        return 2

    width = max(len(r[0]) for r in rows)
    marks = {"regressed": "!!", "tunnel-bound": "~~", "improved": "++",
             "ok": "  "}
    shown = 0
    for key, old, new, delta, tol, status in rows:
        if status == "ok" and not args.all:
            continue
        shown += 1
        note = " (tunnel-bound: informational, never gates)" \
            if status == "tunnel-bound" else ""
        print(f"{marks[status]} {key:<{width}}  {old:>12.4g} -> "
              f"{new:>12.4g}  {delta:+8.2%} (tol {tol:.2%}) "
              f"{status}{note}")
    if shown == 0:
        print(f"all {len(rows)} shared metrics within tolerance")
    print(f"\n{len(rows)} metrics compared; "
          f"{sum(r[5] == 'regressed' for r in rows)} regressed, "
          f"{sum(r[5] == 'tunnel-bound' for r in rows)} tunnel-bound, "
          f"{sum(r[5] == 'improved' for r in rows)} improved")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
